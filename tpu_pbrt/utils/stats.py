"""Statistics registry, profiler, and progress reporting.

Capability match for pbrt-v3 src/core/stats.{h,cpp} and
progressreporter.{h,cpp} (SURVEY.md §5.1/§5.5):
- STAT_COUNTER / STAT_RATIO / STAT_PERCENT / STAT_INT_DISTRIBUTION /
  STAT_MEMORY_COUNTER -> a process-global StatsRegistry with the same
  categorized "Statistics:" report format ("category/Title" strings).
  pbrt's per-thread accumulators + ReportThreadStats merging are
  unnecessary: counts are produced by in-kernel integer reductions
  (summed on device, fetched per chunk) or host-side increments.
- the SIGPROF sampling profiler -> phase timers around the host-side
  chunk loop plus jax.profiler trace hooks (profile_trace()); on TPU the
  per-phase breakdown inside a fused kernel comes from the XLA profile,
  not signal sampling.
- ProgressReporter: same API (update/done), ETA bar on stderr, honoring
  PBRT_PROGRESS_FREQUENCY and quiet mode.
"""

from __future__ import annotations

import sys
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Optional


class StatsRegistry:
    """Global named counters/distributions (stats.cpp StatsAccumulator)."""

    def __init__(self):
        self.counters: Dict[str, int] = defaultdict(int)
        self.memory: Dict[str, int] = defaultdict(int)
        self.ratios: Dict[str, list] = defaultdict(lambda: [0, 0])
        self.percents: Dict[str, list] = defaultdict(lambda: [0, 0])
        self.distributions: Dict[str, list] = defaultdict(lambda: [0, 0, None, None])
        self.phase_times: Dict[str, float] = defaultdict(float)

    # -- STAT_* macro equivalents ----------------------------------------
    def counter(self, name: str, value: int = 1):
        self.counters[name] += int(value)

    def memory_counter(self, name: str, nbytes: int):
        self.memory[name] += int(nbytes)

    def ratio(self, name: str, num: int = 0, denom: int = 0):
        r = self.ratios[name]
        r[0] += int(num)
        r[1] += int(denom)

    def percent(self, name: str, num: int = 0, denom: int = 0):
        p = self.percents[name]
        p[0] += int(num)
        p[1] += int(denom)

    def distribution(self, name: str, value):
        d = self.distributions[name]
        d[0] += float(value)  # float sums: "rays per camera ray" is ~1.x
        d[1] += 1
        d[2] = value if d[2] is None else min(d[2], value)
        d[3] = value if d[3] is None else max(d[3], value)

    @contextmanager
    def phase(self, name: str):
        """ProfilePhase RAII equivalent: wall-time per named phase."""
        t0 = time.time()
        try:
            yield
        finally:
            self.phase_times[name] += time.time() - t0

    def clear(self):
        self.__init__()

    # -- reporting (PrintStats / ReportProfilerResults) ------------------
    def report(self, out=None) -> str:
        lines = ["Statistics:"]
        by_cat = defaultdict(list)

        def add(title, text):
            if "/" in title:
                cat, t = title.split("/", 1)
            else:
                cat, t = "", title
            by_cat[cat].append((t, text))

        for name, v in sorted(self.counters.items()):
            add(name, f"{v:>12d}")
        for name, v in sorted(self.memory.items()):
            mib = v / (1024.0 * 1024.0)
            add(name, f"{mib:>12.2f} MiB")
        for name, (n, d) in sorted(self.ratios.items()):
            if d:
                add(name, f"{n:>12d} / {d:d} ({n / d:.2f}x)")
        for name, (n, d) in sorted(self.percents.items()):
            if d:
                add(name, f"{n:>12d} / {d:d} ({100.0 * n / d:.2f}%)")
        for name, (total, count, mn, mx) in sorted(self.distributions.items()):
            if count:
                add(name, f"{total / count:>12.3f} avg [range {mn} - {mx}]")
        for cat in sorted(by_cat):
            lines.append(f"  {cat or 'Misc'}")
            for t, text in by_cat[cat]:
                lines.append(f"    {t:<42}{text}")
        if self.phase_times:
            total = sum(self.phase_times.values())
            lines.append("  Profile (wall time)")
            for name, secs in sorted(self.phase_times.items(), key=lambda kv: -kv[1]):
                lines.append(f"    {name:<42}{secs:>10.2f}s ({100.0 * secs / max(total, 1e-9):5.1f}%)")
        text = "\n".join(lines)
        if out is not None:
            print(text, file=out)
        return text


STATS = StatsRegistry()


@contextmanager
def profile_trace(log_dir: Optional[str] = None):
    """jax.profiler trace context (TensorBoard/Perfetto), the TPU-side
    replacement for the SIGPROF profiler. No-op when log_dir is None."""
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class ProgressReporter:
    """progressreporter.cpp ProgressReporter: +-style ETA bar. Updates are
    driven by the chunk loop (no background thread needed — chunks complete
    at millisecond-to-second cadence)."""

    def __init__(self, total_work: int, title: str, quiet: bool = False):
        self.total = max(1, int(total_work))
        self.title = title
        self.done_work = 0
        self.start = time.time()
        from tpu_pbrt.config import cfg

        freq = cfg.progress_frequency
        # `is not None`, not truthiness: PBRT_PROGRESS_FREQUENCY=0 means
        # print on every update (pbrt's continuous mode)
        self.min_interval = float(freq) if freq is not None else 0.25
        self.quiet = quiet
        self._last_print = 0.0
        self._printed_len = 0
        if not quiet:
            self._print()

    def update(self, amount: int = 1):
        self.done_work += amount
        now = time.time()
        if not self.quiet and now - self._last_print >= self.min_interval:
            self._print()

    def _print(self):
        self._last_print = time.time()
        frac = min(1.0, self.done_work / self.total)
        elapsed = time.time() - self.start
        eta = elapsed / max(frac, 1e-9) * (1.0 - frac)
        bar_w = 40
        filled = int(bar_w * frac)
        bar = "+" * filled + " " * (bar_w - filled)
        msg = f"\r{self.title}: [{bar}] ({elapsed:.1f}s|{eta:.1f}s)  "
        sys.stderr.write(msg)
        sys.stderr.flush()
        self._printed_len = len(msg)

    def done(self):
        if not self.quiet:
            self.done_work = self.total
            self._print()
            sys.stderr.write("\n")
            sys.stderr.flush()
