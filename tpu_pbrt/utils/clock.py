"""Injectable time source for the serve/dispatch protocol (ISSUE 17).

The render service's scheduling decisions (runnability filters, backoff
deadlines, queue-wait accounting) and the observability recorders
(trace spans, flight heartbeats) all consume time. Before this seam
they sampled the wall clock directly, which made a service run a
function of REAL time — unreproducible, and unexplorable: the protocol
checker (analysis layer 6, `tpu_pbrt/analysis/protocheck.py`) needs a
whole service run to be a pure deterministic function of an explicit
decision sequence.

Two implementations of one small interface:

- ``Clock`` (the module-level ``WALL`` default) — the production wall
  clock. Every method forwards to the stdlib, so a service built
  without an explicit clock behaves byte-identically to the pre-seam
  code.
- ``VirtualClock`` — deterministic simulated time. ``sleep`` advances
  time instead of blocking, and every **decision sample** (``now()``)
  advances time by a small configurable ``tick``, which is what makes
  *hidden* clock samples observable: code that samples the decision
  clock twice where it promised to sample once sees two different
  times, and a deadline falling between the samples reproduces —
  deterministically — the PR 13 ``step()`` double-sample wedge the
  SV-CLOCK lint rule codifies.

The method split is part of the protocol model:

- ``now()`` — a DECISION sample (runnability, deadlines, ready times).
  Ticks virtual time forward.
- ``peek()`` — a pure OBSERVATION (flight-line stamps, invariant
  checks). Never perturbs virtual time, so arming telemetry cannot
  change a virtual run's scheduling decisions.
- ``monotonic()`` — span timing (trace timestamps, device-wait
  attribution). Also non-perturbing under virtual time.
- ``sleep(s)`` — wall: ``time.sleep``; virtual: advance by ``s``.
"""

from __future__ import annotations

import time


class Clock:
    """The production wall clock (and the injectable interface)."""

    def now(self) -> float:
        """Decision-relevant epoch-seconds sample."""
        return time.time()

    def peek(self) -> float:
        """Observation-only epoch-seconds read (never perturbs a
        virtual timeline — see VirtualClock)."""
        return time.time()

    def monotonic(self) -> float:
        """Span-timing read (perf_counter seconds)."""
        return time.perf_counter()

    def sleep(self, seconds: float) -> None:
        time.sleep(max(float(seconds), 0.0))


#: the process default — services/recorders built without an explicit
#: clock sample real time exactly as before the seam existed
WALL = Clock()


class VirtualClock(Clock):
    """Deterministic simulated time for protocol exploration.

    One timeline serves all three read kinds (``now``/``peek``/
    ``monotonic`` — virtual time has no epoch-vs-monotonic split);
    ``now()`` additionally advances it by ``tick`` per sample, modeling
    the real time that passes between two samples of a wall clock.
    ``sleep`` advances instead of blocking, so a backoff window costs
    nothing to wait out and a decision sequence replays in
    microseconds."""

    def __init__(self, start: float = 0.0, tick: float = 1e-6):
        self._t = float(start)
        self.tick = float(tick)
        self.samples = 0  # decision samples taken (now() calls)
        self.sleeps = 0

    def now(self) -> float:
        t = self._t
        self._t = t + self.tick
        self.samples += 1
        return t

    def peek(self) -> float:
        return self._t

    def monotonic(self) -> float:
        return self._t

    def sleep(self, seconds: float) -> None:
        self._t += max(float(seconds), 0.0)
        self.sleeps += 1

    def advance(self, seconds: float) -> None:
        """Explicitly move time forward (an explorer decision)."""
        self._t += max(float(seconds), 0.0)

    def advance_to(self, t: float) -> None:
        """Move time forward to ``t`` (never backward)."""
        self._t = max(self._t, float(t))
