"""Image I/O: EXR, PFM, PNG, TGA, HDR(RGBE) — self-contained codecs.

Capability match for pbrt-v3 src/core/imageio.{h,cpp} (ReadImage/WriteImage
dispatch by extension) and the src/ext/ libraries backing it (OpenEXR,
lodepng, targa). The build environment has no OpenEXR/PIL, so the codecs
are implemented directly: EXR scanline (NONE/ZIPS/ZIP compression, HALF and
FLOAT channels), PNG (zlib + the five scanline filters, 8/16-bit,
gray/RGB/alpha/palette), TGA (types 2/10, 24/32bpp), Radiance RGBE, PFM.

Convention matches pbrt: ReadImage returns linear RGB float32 (H,W,3) with
8-bit LDR formats inverse-gamma'd from sRGB; WriteImage takes linear RGB and
gamma-encodes when writing LDR formats.
"""

from __future__ import annotations

import os
import struct
import zlib

import numpy as np

from tpu_pbrt.utils.error import Error, Warning


# -------------------------------------------------------------------------
# sRGB transfer (pbrt GammaCorrect / InverseGammaCorrect)
# -------------------------------------------------------------------------

def gamma_correct(v):
    v = np.clip(v, 0.0, 1.0)
    return np.where(v <= 0.0031308, 12.92 * v, 1.055 * np.power(v, 1.0 / 2.4) - 0.055)


def inverse_gamma_correct(v):
    return np.where(v <= 0.04045, v / 12.92, np.power((v + 0.055) / 1.055, 2.4))


# -------------------------------------------------------------------------
# EXR
# -------------------------------------------------------------------------

_EXR_MAGIC = 20000630
_PT_UINT, _PT_HALF, _PT_FLOAT = 0, 1, 2


def _exr_attr(name: str, type_name: str, data: bytes) -> bytes:
    return (
        name.encode() + b"\0" + type_name.encode() + b"\0" + struct.pack("<i", len(data)) + data
    )


def write_exr(path: str, img: np.ndarray, half: bool = True):
    """Scanline EXR, ZIP-compressed blocks of 16, channels B,G,R."""
    img = np.asarray(img, np.float32)
    if img.ndim == 2:
        img = img[..., None].repeat(3, axis=-1)
    h, w = img.shape[:2]
    pt = _PT_HALF if half else _PT_FLOAT
    psz = 2 if half else 4
    chans = b""
    for name in (b"B", b"G", b"R"):  # alphabetical, as required
        chans += name + b"\0" + struct.pack("<iiii", pt, 0, 1, 1)
    chans += b"\0"
    header = b""
    header += _exr_attr("channels", "chlist", chans)
    header += _exr_attr("compression", "compression", struct.pack("<B", 3))  # ZIP
    header += _exr_attr("dataWindow", "box2i", struct.pack("<iiii", 0, 0, w - 1, h - 1))
    header += _exr_attr("displayWindow", "box2i", struct.pack("<iiii", 0, 0, w - 1, h - 1))
    header += _exr_attr("lineOrder", "lineOrder", struct.pack("<B", 0))
    header += _exr_attr("pixelAspectRatio", "float", struct.pack("<f", 1.0))
    header += _exr_attr("screenWindowCenter", "v2f", struct.pack("<ff", 0.0, 0.0))
    header += _exr_attr("screenWindowWidth", "float", struct.pack("<f", 1.0))
    header += b"\0"

    dtype = np.float16 if half else np.float32
    n_blocks = (h + 15) // 16
    blocks = []
    for bi in range(n_blocks):
        y0 = bi * 16
        rows = min(16, h - y0)
        raw = bytearray()
        for y in range(y0, y0 + rows):
            for c in (2, 1, 0):  # B, G, R
                raw += img[y, :, c].astype(dtype).tobytes()
        raw = bytes(raw)
        # EXR zip preprocess: interleave-split then delta encode
        a = np.frombuffer(raw, np.uint8)
        half_len = (len(a) + 1) // 2
        inter = np.empty_like(a)
        inter[:half_len] = a[0::2]
        inter[half_len:] = a[1::2]
        d = inter.astype(np.int16)
        d[1:] = d[1:] - d[:-1] + (-128 + 256)
        enc = (d & 0xFF).astype(np.uint8).tobytes()
        comp = zlib.compress(enc, 6)
        if len(comp) >= len(raw):
            comp = raw  # stored uncompressed when bigger (per spec)
        blocks.append((y0, comp))

    out = bytearray()
    out += struct.pack("<ii", _EXR_MAGIC, 2)
    out += header
    offset_table_pos = len(out)
    out += b"\0" * (8 * n_blocks)
    offsets = []
    for y0, comp in blocks:
        offsets.append(len(out))
        out += struct.pack("<ii", y0, len(comp)) + comp
    for i, off in enumerate(offsets):
        struct.pack_into("<Q", out, offset_table_pos + 8 * i, off)
    with open(path, "wb") as f:
        f.write(bytes(out))


def _exr_unpredict(data: bytes) -> bytes:
    d = np.frombuffer(data, np.uint8).astype(np.int16)
    d[1:] += -128
    d = np.cumsum(d, dtype=np.int64) % 256  # delta decode
    d = d.astype(np.uint8)
    # de-interleave: first half -> even positions
    out = np.empty_like(d)
    half_len = (len(d) + 1) // 2
    out[0::2] = d[:half_len]
    out[1::2] = d[half_len:]
    return out.tobytes()


def read_exr(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        data = f.read()
    magic, version = struct.unpack_from("<ii", data, 0)
    if magic != _EXR_MAGIC:
        Error(f"{path}: not an EXR file")
    if version & 0x200:
        Error(f"{path}: tiled EXR not supported")
    pos = 8
    channels = []
    compression = 0
    dw = (0, 0, 0, 0)
    while True:
        if data[pos] == 0:
            pos += 1
            break
        e = data.index(b"\0", pos)
        name = data[pos:e].decode()
        pos = e + 1
        e = data.index(b"\0", pos)
        tname = data[pos:e].decode()
        pos = e + 1
        (sz,) = struct.unpack_from("<i", data, pos)
        pos += 4
        payload = data[pos : pos + sz]
        pos += sz
        if name == "channels":
            cp = 0
            while payload[cp] != 0:
                ce = payload.index(b"\0", cp)
                cname = payload[cp:ce].decode()
                cp = ce + 1
                ptype, _, xs, ys = struct.unpack_from("<iiii", payload, cp)
                cp += 16
                channels.append((cname, ptype, xs, ys))
            if any(c[2] != 1 or c[3] != 1 for c in channels):
                Error(f"{path}: subsampled channels not supported")
        elif name == "compression":
            compression = payload[0]
        elif name == "dataWindow":
            dw = struct.unpack("<iiii", payload)
    w = dw[2] - dw[0] + 1
    h = dw[3] - dw[1] + 1
    if compression not in (0, 2, 3):
        Error(f"{path}: EXR compression mode {compression} not supported (use none/zip)")
    rows_per_block = {0: 1, 2: 1, 3: 16}[compression]
    n_blocks = (h + rows_per_block - 1) // rows_per_block
    offsets = struct.unpack_from(f"<{n_blocks}Q", data, pos)
    dtypes = {_PT_UINT: np.uint32, _PT_HALF: np.float16, _PT_FLOAT: np.float32}
    bpp = {_PT_UINT: 4, _PT_HALF: 2, _PT_FLOAT: 4}
    row_bytes = sum(bpp[c[1]] for c in channels) * w
    planes = {c[0]: np.zeros((h, w), np.float32) for c in channels}
    for off in offsets:
        y, sz = struct.unpack_from("<ii", data, off)
        y -= dw[1]
        payload = data[off + 8 : off + 8 + sz]
        rows = min(rows_per_block, h - y)
        expect = row_bytes * rows
        if compression and sz != expect:
            payload = _exr_unpredict(zlib.decompress(payload))
        p = 0
        for r in range(rows):
            for cname, ptype, _, _ in channels:  # alphabetical within a row
                n = bpp[ptype] * w
                vals = np.frombuffer(payload[p : p + n], dtypes[ptype]).astype(np.float32)
                planes[cname][y + r] = vals
                p += n
    if all(k in planes for k in ("R", "G", "B")):
        return np.stack([planes["R"], planes["G"], planes["B"]], axis=-1)
    if "Y" in planes:
        return planes["Y"][..., None].repeat(3, axis=-1)
    first = next(iter(planes.values()))
    return first[..., None].repeat(3, axis=-1)


# -------------------------------------------------------------------------
# PFM
# -------------------------------------------------------------------------

def write_pfm(path: str, img: np.ndarray):
    img = np.asarray(img, np.float32)
    h, w = img.shape[:2]
    color = img.ndim == 3 and img.shape[2] == 3
    with open(path, "wb") as f:
        f.write(b"PF\n" if color else b"Pf\n")
        f.write(f"{w} {h}\n".encode())
        f.write(b"-1.000000\n")  # little-endian
        f.write(img[::-1].astype("<f4").tobytes())  # bottom-up rows


def read_pfm(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        kind = f.readline().strip()
        dims = f.readline().split()
        scale = float(f.readline())
        w, h = int(dims[0]), int(dims[1])
        nc = 3 if kind == b"PF" else 1
        dt = "<f4" if scale < 0 else ">f4"
        a = np.frombuffer(f.read(4 * w * h * nc), dt).reshape(h, w, nc)[::-1]
    a = a.astype(np.float32) * abs(scale)
    return a.repeat(3, axis=-1) if nc == 1 else a.copy()


# -------------------------------------------------------------------------
# PNG
# -------------------------------------------------------------------------

def write_png(path: str, img8: np.ndarray):
    """img8: (H,W,3) uint8."""
    h, w = img8.shape[:2]
    raw = b"".join(b"\x00" + img8[y].tobytes() for y in range(h))

    def chunk(tag, payload):
        c = tag + payload
        return struct.pack(">I", len(payload)) + c + struct.pack(">I", zlib.crc32(c))

    ihdr = struct.pack(">IIBBBBB", w, h, 8, 2, 0, 0, 0)
    with open(path, "wb") as f:
        f.write(b"\x89PNG\r\n\x1a\n")
        f.write(chunk(b"IHDR", ihdr))
        f.write(chunk(b"IDAT", zlib.compress(raw, 6)))
        f.write(chunk(b"IEND", b""))


def _png_unfilter(raw: np.ndarray, h: int, stride: int, fpp: int) -> np.ndarray:
    out = np.zeros((h, stride), np.uint8)
    pos = 0
    prev = np.zeros(stride, np.int32)
    for y in range(h):
        ft = raw[pos]
        pos += 1
        row = raw[pos : pos + stride].astype(np.int32)
        pos += stride
        if ft == 0:
            cur = row
        elif ft == 1:  # sub
            cur = row.copy()
            for i in range(fpp, stride):
                cur[i] = (cur[i] + cur[i - fpp]) & 0xFF
        elif ft == 2:  # up
            cur = (row + prev) & 0xFF
        elif ft == 3:  # average
            cur = row.copy()
            for i in range(stride):
                left = cur[i - fpp] if i >= fpp else 0
                cur[i] = (cur[i] + ((left + prev[i]) >> 1)) & 0xFF
        elif ft == 4:  # paeth
            cur = row.copy()
            for i in range(stride):
                a = cur[i - fpp] if i >= fpp else 0
                b = prev[i]
                c = prev[i - fpp] if i >= fpp else 0
                p = a + b - c
                pa, pb, pc = abs(p - a), abs(p - b), abs(p - c)
                pred = a if (pa <= pb and pa <= pc) else (b if pb <= pc else c)
                cur[i] = (cur[i] + pred) & 0xFF
        else:
            Error(f"PNG: bad filter type {ft}")
        out[y] = cur.astype(np.uint8)
        prev = cur
    return out


def read_png(path: str) -> np.ndarray:
    """Returns linear RGB float32 (inverse sRGB applied to 8/16-bit data)."""
    with open(path, "rb") as f:
        data = f.read()
    if data[:8] != b"\x89PNG\r\n\x1a\n":
        Error(f"{path}: not a PNG")
    pos = 8
    idat = b""
    plte = None
    w = h = depth = ctype = interlace = 0
    while pos < len(data):
        (ln,) = struct.unpack_from(">I", data, pos)
        tag = data[pos + 4 : pos + 8]
        payload = data[pos + 8 : pos + 8 + ln]
        pos += 12 + ln
        if tag == b"IHDR":
            w, h, depth, ctype, _, _, interlace = struct.unpack(">IIBBBBB", payload)
        elif tag == b"PLTE":
            plte = np.frombuffer(payload, np.uint8).reshape(-1, 3)
        elif tag == b"IDAT":
            idat += payload
        elif tag == b"IEND":
            break
    if interlace:
        Error(f"{path}: interlaced PNG not supported")
    nchan = {0: 1, 2: 3, 3: 1, 4: 2, 6: 4}[ctype]
    bypp = max(1, depth // 8) * nchan
    stride = (w * depth * nchan + 7) // 8
    raw = np.frombuffer(zlib.decompress(idat), np.uint8)
    rows = _png_unfilter(raw, h, stride, bypp)
    if depth == 8:
        px = rows.reshape(h, stride)[:, : w * nchan].reshape(h, w, nchan).astype(np.float32) / 255.0
    elif depth == 16:
        px = rows.reshape(h, -1).view(">u2")[:, : w * nchan].reshape(h, w, nchan).astype(np.float32) / 65535.0
    elif depth in (1, 2, 4) and ctype in (0, 3):
        # unpack sub-byte samples
        bits = np.unpackbits(rows, axis=1)
        spb = depth
        vals = np.zeros((h, w), np.int32)
        for b in range(spb):
            vals = (vals << 1) | bits[:, b::spb][:, :w]
        px = (vals.astype(np.float32) / ((1 << depth) - 1))[..., None]
    else:
        Error(f"{path}: unsupported PNG depth {depth}")
    if ctype == 3:
        idx = (px[..., 0] * 255 if depth == 8 else px[..., 0] * ((1 << depth) - 1)).astype(np.int32)
        px = plte[idx].astype(np.float32) / 255.0
    if px.shape[2] == 1:
        px = px.repeat(3, axis=-1)
    elif px.shape[2] == 2:
        px = px[..., :1].repeat(3, axis=-1)
    elif px.shape[2] == 4:
        px = px[..., :3]
    return inverse_gamma_correct(px).astype(np.float32)


# -------------------------------------------------------------------------
# TGA
# -------------------------------------------------------------------------

def read_tga(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        data = f.read()
    idlen, cmap_type, img_type = data[0], data[1], data[2]
    w, h = struct.unpack_from("<HH", data, 12)
    bpp = data[16]
    desc = data[17]
    pos = 18 + idlen + (struct.unpack_from("<H", data, 5)[0] * ((data[7] + 7) // 8) if cmap_type else 0)
    nb = bpp // 8
    if img_type in (2, 3):
        px = np.frombuffer(data, np.uint8, w * h * nb, pos).reshape(h, w, nb)
    elif img_type in (10, 11):
        out = np.zeros((h * w, nb), np.uint8)
        i = 0
        while i < h * w:
            hdr = data[pos]
            pos += 1
            cnt = (hdr & 0x7F) + 1
            if hdr & 0x80:
                out[i : i + cnt] = np.frombuffer(data, np.uint8, nb, pos)
                pos += nb
            else:
                out[i : i + cnt] = np.frombuffer(data, np.uint8, cnt * nb, pos).reshape(cnt, nb)
                pos += cnt * nb
            i += cnt
        px = out.reshape(h, w, nb)
    else:
        Error(f"{path}: TGA type {img_type} not supported")
    if not (desc & 0x20):  # bottom-up origin
        px = px[::-1]
    if nb >= 3:
        px = px[..., [2, 1, 0]]  # BGR -> RGB
    else:
        px = px[..., :1].repeat(3, axis=-1)
    return inverse_gamma_correct(px.astype(np.float32) / 255.0).astype(np.float32)


# -------------------------------------------------------------------------
# Radiance HDR (RGBE)
# -------------------------------------------------------------------------

def read_hdr(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while True:
        e = data.index(b"\n", pos)
        line = data[pos:e]
        pos = e + 1
        if line == b"":
            break
    e = data.index(b"\n", pos)
    dims = data[pos:e].split()
    pos = e + 1
    if dims[0] != b"-Y" or dims[2] != b"+X":
        Error(f"{path}: unsupported HDR orientation")
    h, w = int(dims[1]), int(dims[3])
    rgbe = np.zeros((h, w, 4), np.uint8)
    for y in range(h):
        if w >= 8 and w < 32768 and data[pos] == 2 and data[pos + 1] == 2:
            pos += 4
            for c in range(4):
                x = 0
                while x < w:
                    cnt = data[pos]
                    pos += 1
                    if cnt > 128:
                        rgbe[y, x : x + cnt - 128, c] = data[pos]
                        pos += 1
                        x += cnt - 128
                    else:
                        rgbe[y, x : x + cnt, c] = np.frombuffer(data, np.uint8, cnt, pos)
                        pos += cnt
                        x += cnt
        else:
            rgbe[y] = np.frombuffer(data, np.uint8, w * 4, pos).reshape(w, 4)
            pos += w * 4
    exp = rgbe[..., 3].astype(np.int32) - 128 - 8
    scale = np.ldexp(1.0, exp).astype(np.float32)
    return (rgbe[..., :3].astype(np.float32) * scale[..., None]).astype(np.float32)


# -------------------------------------------------------------------------
# dispatch (pbrt ReadImage / WriteImage)
# -------------------------------------------------------------------------

def read_image(path: str, gamma: bool = None) -> np.ndarray:
    """-> linear RGB float32 (H,W,3)."""
    ext = os.path.splitext(path)[1].lower()
    if ext == ".exr":
        return read_exr(path)
    if ext == ".pfm":
        return read_pfm(path)
    if ext == ".png":
        return read_png(path)
    if ext == ".tga":
        return read_tga(path)
    if ext == ".hdr":
        return read_hdr(path)
    Error(f'unable to load image stored in format "{ext}" for filename "{path}"')


def write_image(path: str, img: np.ndarray):
    """img: linear RGB float32."""
    ext = os.path.splitext(path)[1].lower()
    if ext == ".exr":
        return write_exr(path, img)
    if ext == ".pfm":
        return write_pfm(path, img)
    if ext in (".png", ""):
        img8 = (gamma_correct(np.asarray(img)) * 255.0 + 0.5).astype(np.uint8)
        return write_png(path if ext else path + ".png", img8)
    if ext == ".tga":
        img8 = (gamma_correct(np.asarray(img)) * 255.0 + 0.5).astype(np.uint8)
        h, w = img8.shape[:2]
        with open(path, "wb") as f:
            f.write(struct.pack("<BBBHHBHHHHBB", 0, 0, 2, 0, 0, 0, 0, 0, w, h, 24, 0x20))
            f.write(img8[..., [2, 1, 0]].tobytes())
        return
    Warning(f'format of "{path}" unknown; writing PNG')
    img8 = (gamma_correct(np.asarray(img)) * 255.0 + 0.5).astype(np.uint8)
    write_png(path + ".png", img8)
