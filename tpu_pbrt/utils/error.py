"""Warning/Error reporting with scene-file locations.

Capability match for pbrt-v3 src/core/error.{h,cpp} (Warning/Error with
file:line from parser state) plus glog-style severity logging via the
stdlib logging module.
"""

from __future__ import annotations

import logging
import sys

logger = logging.getLogger("tpu_pbrt")
if not logger.handlers:
    _h = logging.StreamHandler(sys.stderr)
    _h.setFormatter(logging.Formatter("%(levelname)s %(name)s: %(message)s"))
    logger.addHandler(_h)
    logger.setLevel(logging.WARNING)

# current parse location, maintained by the parser (file, line)
_parse_loc: list = []
_quiet = False
_n_warnings = 0


class PbrtError(RuntimeError):
    pass


def set_quiet(q: bool):
    global _quiet
    _quiet = q


def push_loc(filename: str, line: int = 0):
    _parse_loc.append([filename, line])


def set_line(line: int):
    if _parse_loc:
        _parse_loc[-1][1] = line


def pop_loc():
    if _parse_loc:
        _parse_loc.pop()


def _loc() -> str:
    if _parse_loc:
        f, l = _parse_loc[-1]
        return f"{f}:{l}: "
    return ""


def Warning(msg: str):  # noqa: N802 - pbrt API name
    global _n_warnings
    _n_warnings += 1
    if not _quiet:
        logger.warning("%s%s", _loc(), msg)


def Error(msg: str):  # noqa: N802 - pbrt API name
    logger.error("%s%s", _loc(), msg)
    raise PbrtError(_loc() + msg)


def info(msg: str):
    logger.info("%s", msg)
