#!/usr/bin/env bash
# Local CI gate (ISSUE 2 + ISSUE 3 satellites):
#   ruff -> jaxlint (AST) -> jaxpr audit + jaxcost budget gate + shardcheck
#   -> tier-1 pytest.
#
#   tools/ci.sh            # full gate
#   tools/ci.sh --fast     # skip the pytest leg (lint + audit + gates only)
#
# ruff is optional in minimal containers (the image does not bake it);
# the repo-specific invariants are enforced by `python -m
# tpu_pbrt.analysis` regardless. The jaxcost budget gate compares the
# entry-point static rooflines against the committed
# tpu_pbrt/analysis/budgets.json — a perf regression fails HERE even
# when no accelerator is reachable (the BENCH_r05 outage class); after
# an INTENTIONAL hot-path change refresh with
# `python -m tpu_pbrt.analysis --update-budgets` and commit the file.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== ruff"
if command -v ruff >/dev/null 2>&1; then
    ruff check tpu_pbrt tests bench.py
elif python -c "import ruff" >/dev/null 2>&1; then
    python -m ruff check tpu_pbrt tests bench.py
else
    echo "   ruff not installed — skipping (pip install ruff to enable)"
fi

# fail-FAST stage: the AST lint costs ~2 s with no jax import; a lint
# error aborts here before the multi-minute trace/compile stages below
# (which re-lint — the duplication is the price of the early exit)
echo "== jaxlint AST layer (python -m tpu_pbrt.analysis --no-audit --no-cost --no-shardcheck)"
python -m tpu_pbrt.analysis --no-audit --no-cost --no-shardcheck

echo "== jaxpr audit + jaxcost budget gate + shardcheck (python -m tpu_pbrt.analysis)"
python -m tpu_pbrt.analysis

if [[ "${1:-}" == "--fast" ]]; then
    echo "== pytest skipped (--fast)"
    exit 0
fi

echo "== tier-1 pytest"
python -m pytest tests/ -q -m 'not slow' -p no:cacheprovider
