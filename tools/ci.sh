#!/usr/bin/env bash
# Local CI gate (ISSUE 2 satellite): ruff -> jaxlint -> tier-1 pytest.
#
#   tools/ci.sh            # full gate
#   tools/ci.sh --fast     # skip the pytest leg (lint + audit only)
#
# ruff is optional in minimal containers (the image does not bake it);
# the repo-specific invariants are enforced by `python -m
# tpu_pbrt.analysis` regardless.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== ruff"
if command -v ruff >/dev/null 2>&1; then
    ruff check tpu_pbrt tests bench.py
elif python -c "import ruff" >/dev/null 2>&1; then
    python -m ruff check tpu_pbrt tests bench.py
else
    echo "   ruff not installed — skipping (pip install ruff to enable)"
fi

echo "== jaxlint (python -m tpu_pbrt.analysis)"
python -m tpu_pbrt.analysis

if [[ "${1:-}" == "--fast" ]]; then
    echo "== pytest skipped (--fast)"
    exit 0
fi

echo "== tier-1 pytest"
python -m pytest tests/ -q -m 'not slow' -p no:cacheprovider
