#!/usr/bin/env bash
# Local CI gate (ISSUE 2 + 3 + 11 + 15 + 17 + 18 + 19 + 20):
#   ruff -> jaxlint (AST) -> jaxpr audit + jaxcost budget gate + shardcheck
#   + pallascheck VMEM/grid-semantics gate + protocheck protocol lint
#   + hbmcheck HBM residency/liveness/capacity gate
#   -> telemetry/chaos/serve smokes
#   -> tpu-scope (timeline reconstruction + health verb + bench gate)
#   -> protocheck explorer smoke (bounded interleaving/fault search)
#   -> tpu-load traffic replay + fleet router smokes (baseline-diffed)
#   -> tier-1 pytest.
#
#   tools/ci.sh            # full gate
#   tools/ci.sh --fast     # skip the pytest leg (lint + audit + gates only)
#
# ruff is optional in minimal containers (the image does not bake it);
# the repo-specific invariants are enforced by `python -m
# tpu_pbrt.analysis` regardless. The jaxcost budget gate compares the
# entry-point static rooflines against the committed
# tpu_pbrt/analysis/budgets.json — a perf regression fails HERE even
# when no accelerator is reachable (the BENCH_r05 outage class); after
# an INTENTIONAL hot-path change refresh with
# `python -m tpu_pbrt.analysis --update-budgets` and commit the file.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== ruff"
if command -v ruff >/dev/null 2>&1; then
    ruff check tpu_pbrt tests bench.py
elif python -c "import ruff" >/dev/null 2>&1; then
    python -m ruff check tpu_pbrt tests bench.py
else
    echo "   ruff not installed — skipping (pip install ruff to enable)"
fi

# fail-FAST stage: the AST lint costs ~2 s with no jax import; a lint
# error aborts here before the multi-minute trace/compile stages below
# (which re-lint — the duplication is the price of the early exit).
# --no-protocheck/--no-hbmcheck too: layers 6-7 spin up real
# RenderServices / evaluate the serve memory model, so they belong with
# the heavier stages, not the syntax gate.
echo "== jaxlint AST layer (python -m tpu_pbrt.analysis --no-audit --no-cost --no-shardcheck --no-pallascheck --no-protocheck --no-hbmcheck)"
python -m tpu_pbrt.analysis --no-audit --no-cost --no-shardcheck --no-pallascheck --no-protocheck --no-hbmcheck

# the full analysis stage runs every layer and reports EVERY failing
# stage before exiting non-zero (ISSUE 11 satellite). pallascheck gates
# the fused kernels' per-grid-step VMEM footprints against the
# committed vmem_budgets.json, verifies grid semantics (PC-RACE/
# PC-INIT/PC-OOB) and re-derives the fused caps from the VMEM model
# (PC-CAPS); after an INTENTIONAL kernel change refresh BOTH budget
# files with `python -m tpu_pbrt.analysis --update-budgets`.
# (layer 6, protocheck, also runs here: SV-* protocol lint + the
# mutation-regression corpus + a small bounded exploration. layer 7,
# hbmcheck, gates the serve stack's static HBM model — worst-case
# footprint vs the platform capacity table + the committed
# hbm_budgets.json, terminal-path buffer release, residency-estimate
# accuracy, donation-alias dedup.)
echo "== jaxpr audit + jaxcost budget gate + shardcheck + pallascheck + protocheck + hbmcheck (python -m tpu_pbrt.analysis)"
python -m tpu_pbrt.analysis

# telemetry smoke (ISSUE 4): render a cropped cornell through the real
# CLI with --trace + the flight recorder, then gate on the artifacts —
# the trace JSON must validate against the Chrome-trace schema and the
# flight JSONL must carry >= 1 heartbeat for every render phase.
echo "== telemetry smoke: --trace render + trace/flight validation"
SMOKE_DIR="$(mktemp -d)"
trap 'rm -rf "$SMOKE_DIR"' EXIT
XLA_FLAGS="${XLA_FLAGS:-} --xla_backend_optimization_level=0" \
TPU_PBRT_FLIGHT_PATH="$SMOKE_DIR/flight.jsonl" \
python -m tpu_pbrt.main scenes/cornell-path.pbrt --quick --quiet \
    --cropwindow 0 0.25 0 0.25 \
    -o "$SMOKE_DIR/smoke.pfm" --trace "$SMOKE_DIR/trace.json" \
    --metrics-path "$SMOKE_DIR/metrics.prom"
python -m tpu_pbrt.obs "$SMOKE_DIR/trace.json" \
    --flight "$SMOKE_DIR/flight.jsonl" \
    --require-phases render,render_done,develop --min-spans 3 \
    --metrics "$SMOKE_DIR/metrics.prom"

# fused-kernel interpret-mode smoke (ISSUE 9): render a small scene
# with TPU_PBRT_FUSED=1 (Pallas wavefront kernels, interpret mode on
# CPU) and bit-compare against the jnp path, through a mid-render
# dispatch fault so the recovery ladder runs over the fused program.
# Implemented as the chaos matrix's fused-tracer row; running it alone
# first gives a fast, named failure before the full matrix below. The
# row uses a killeroo-like scene, not cornell: cornell compiles to the
# brute MXU path and never touches the stream tracer being swapped.
echo "== fused wavefront kernel smoke (python -m tpu_pbrt.chaos --only fused-tracer)"
python -m tpu_pbrt.chaos --only fused-tracer

# pipelined-dispatch smoke (ISSUE 13): a poisoning dispatch loss with
# TPU_PBRT_PIPELINE=3 chunk-slices in flight must flush the window,
# roll back to a deferred-written checkpoint and recover a film
# bit-identical to the undisturbed render. Standalone first for a fast,
# named failure; the full matrix below re-runs it under the explicit
# default depth.
echo "== pipelined dispatch smoke (python -m tpu_pbrt.chaos --only pipeline)"
TPU_PBRT_PIPELINE=2 python -m tpu_pbrt.chaos --only pipeline

# chaos recovery matrix (ISSUE 5): every fault scenario — poisoned/clean
# dispatch loss, torn/crashed/bit-flipped checkpoint writes, corrupt
# checkpoint resume, NaN wave, retry-budget exhaustion, mesh device
# loss, plus the ISSUE 20 fleet rows (replica killed mid-job resumes
# elsewhere from the spool; a restarted router adopts the replicas)
# — must recover to a film BIT-identical to the undisturbed render
# (the nan-wave-scrub row instead gates the degrade semantics: finite
# image + nonfinite_deposits>0). Runs on CPU; no accelerator needed.
# TPU_PBRT_PIPELINE=2 is the default, exported explicitly so the gate
# keeps covering the pipelined drain even if the default ever moves
echo "== chaos recovery matrix (python -m tpu_pbrt.chaos)"
TPU_PBRT_PIPELINE=2 python -m tpu_pbrt.chaos

# render-service smoke (ISSUE 6 + ISSUE 10 + ISSUE 15): submit two
# cropped cornell jobs to one service, preempt/resume one mid-render,
# and require both films finite AND bit-identical to a solo
# run-to-completion render, a warm resubmit with 0 scene compiles + 0
# jit retraces, >= 1 streamed preview, a DETERMINISTIC shed count from
# an over-SLO submit burst, a lint-clean Prometheus metrics exposition
# with per-tenant histograms, trace-id exemplars on the slice
# histogram, and a clean health-watchdog verdict. The run is
# tracing-armed (TPU_PBRT_TRACE_PATH/FLIGHT_PATH) so the next stage can
# reconstruct its job timelines.
echo "== render service smoke, tracing-armed (python -m tpu_pbrt.serve --selftest)"
XLA_FLAGS="${XLA_FLAGS:-} --xla_backend_optimization_level=0" \
TPU_PBRT_TRACE_PATH="$SMOKE_DIR/serve_trace.json" \
TPU_PBRT_FLIGHT_PATH="$SMOKE_DIR/serve_flight.jsonl" \
TPU_PBRT_PIPELINE=2 python -m tpu_pbrt.serve --selftest

# tpu-scope stage (ISSUE 15): (1) rebuild every job's causal timeline
# from the selftest's trace + per-job flight exports and require it
# complete — paired job/wait/slice async spans, bound dispatch->retire
# flow arrows, ok-retired coverage of every chunk, flight heartbeats
# joined by trace id; (2) round-trip the JSONL daemon's `health` verb
# (the watchdog must report ok on an idle service — the chaos matrix
# above already proved the wedge/backoff-storm rows DO flag it);
# (3) the bench regression gate's selftest: baseline self-pass, infra
# outage exemption, synthetic 50% regression caught by metric name.
echo "== tpu-scope: timeline reconstruction + health verb + bench gate"
python tools/scope.py "$SMOKE_DIR/serve_trace.json" \
    --flight "$SMOKE_DIR/serve_flight.jsonl" --check
printf '%s\n' '{"op": "health"}' '{"op": "shutdown"}' \
    | python -m tpu_pbrt.serve > "$SMOKE_DIR/health.jsonl"
python - "$SMOKE_DIR/health.jsonl" <<'EOF'
import json, sys
docs = [json.loads(x) for x in open(sys.argv[1]) if x.strip()]
rep = next(d for d in docs if d.get("op") == "health")
assert rep["ok"] and rep["firing"] == [], rep
names = {c["name"] for c in rep["conditions"]}
assert names == {"wedge", "backoff_storm", "slo_burn", "nonfinite_spike"}, names
print(f"health verb OK ({len(names)} conditions, none firing)")
EOF
python tools/bench_gate.py --selftest

# protocheck explorer smoke (ISSUE 17): a bounded exhaustive search
# over decision sequences — arrival orders x pipeline depths 1-3 x
# CHAOS fault placements x preempt/resume timings — running the REAL
# RenderService under a VirtualClock with stub dispatches, checking
# every PROTO-* invariant after every decision plus the PROTO-DET
# byte-identical-replay gate. Fixed seed and node/depth budget: the
# whole grid completes in seconds, well under the 60 s CI allowance.
# The exported canonical-drain trace carries virtual-time stamps
# (otherData.clock = "virtual"); scope --check must accept it.
echo "== protocheck explorer smoke (python tools/explore.py --ci)"
python tools/explore.py --ci --seed 0 --nodes 40 --depth 7 \
    --trace-out "$SMOKE_DIR/explore_trace.json"
python tools/scope.py "$SMOKE_DIR/explore_trace.json" --check

# tpu-load smoke (ISSUE 19): seeded traffic scenarios replayed against
# the REAL RenderService in accelerated virtual time — determinism
# (byte-identical decision logs across same-seed replays), burst shed
# fraction + per-class p99 queue waits within spec, zero health-
# watchdog false positives on clean scenarios (required flags on the
# storm ones), pin balance at drain, and a capacity-sweep knee. Fixed
# seed, hard wall budget. The exported trace carries dense multi-
# tenant traffic in virtual time; scope --check must accept it. The
# deterministic gate report is diffed against the committed baseline
# the way BENCH_REPORT.md diffs captures; after an INTENTIONAL
# scheduling/policy change refresh with:
#   python -m tpu_pbrt.load --ci --seed 7 --report LOADTEST_baseline.json
echo "== tpu-load traffic-replay smoke (python -m tpu_pbrt.load --ci)"
python -m tpu_pbrt.load --ci --seed 7 --budget-s 120 \
    --report "$SMOKE_DIR/load_report.json" \
    --trace-out "$SMOKE_DIR/load_trace.json"
python tools/scope.py "$SMOKE_DIR/load_trace.json" --check
if ! diff -u LOADTEST_baseline.json "$SMOKE_DIR/load_report.json"; then
    echo "   LOADTEST_baseline.json is stale — gate outcomes moved (see"
    echo "   diff above); refresh after an INTENTIONAL policy change:"
    echo "   python -m tpu_pbrt.load --ci --seed 7 --report LOADTEST_baseline.json"
    exit 1
fi

# tpu-fleet stage (ISSUE 20): replicated serve behind the failover
# router. (1) the fleet selftest — two REAL in-process replicas under
# one VirtualClock: scene-affinity routing with a residency warm hit,
# fleet-edge shedding at a clamped knee, and a kill-one failover whose
# resumed film is BIT-identical to the undisturbed solo render — with
# tracing armed so (2) scope --check validates the cross-replica
# timeline (router-owned root spans spanning the re-route). (3) the
# seeded router mutant: a failover that re-submits WITHOUT consuming
# the old instance must be flagged by PROTO-ROUTE-DUP by name
# (--mutate exits 1 on detection, so the gate inverts). (4) the
# multi-replica load smoke: the same seeded workloads replayed through
# the router at --replicas 2, decision logs byte-deterministic per
# (spec, seed, N), gates evaluated fleet-wide, report diffed against
# the committed baseline; after an INTENTIONAL routing/policy change:
#   python -m tpu_pbrt.load --scenario steady --scenario heavy \
#     --scenario editstorm --replicas 2 --seed 7 --report FLEET_baseline.json
echo "== fleet router smoke, tracing-armed (python -m tpu_pbrt.fleet --selftest)"
XLA_FLAGS="${XLA_FLAGS:-} --xla_backend_optimization_level=0" \
TPU_PBRT_TRACE_PATH="$SMOKE_DIR/fleet_trace.json" \
python -m tpu_pbrt.fleet --selftest
python tools/scope.py "$SMOKE_DIR/fleet_trace.json" --check
echo "== fleet failover-dedup mutant (python tools/explore.py --mutate failover-skips-spool-consume)"
if python tools/explore.py --mutate failover-skips-spool-consume > "$SMOKE_DIR/fleet_mutant.log" 2>&1; then
    echo "   seeded failover-dedup mutant NOT detected — PROTO-ROUTE-DUP gate rotted"
    cat "$SMOKE_DIR/fleet_mutant.log"
    exit 1
fi
grep -q "PROTOCHECK VIOLATION PROTO-ROUTE-DUP" "$SMOKE_DIR/fleet_mutant.log" || {
    echo "   mutant flagged, but not by PROTO-ROUTE-DUP:"
    cat "$SMOKE_DIR/fleet_mutant.log"
    exit 1
}
echo "== fleet multi-replica load smoke (python -m tpu_pbrt.load --replicas 2)"
python -m tpu_pbrt.load --scenario steady --scenario heavy \
    --scenario editstorm --replicas 2 --seed 7 \
    --report "$SMOKE_DIR/fleet_report.json"
if ! diff -u FLEET_baseline.json "$SMOKE_DIR/fleet_report.json"; then
    echo "   FLEET_baseline.json is stale — routed gate outcomes moved"
    echo "   (see diff above); refresh after an INTENTIONAL change:"
    echo "   python -m tpu_pbrt.load --scenario steady --scenario heavy --scenario editstorm --replicas 2 --seed 7 --report FLEET_baseline.json"
    exit 1
fi

# hbm leak-mutant smoke (ISSUE 18): re-introduce the seeded park-path
# film leak through the REAL entry point and require PROTO-HBM to flag
# it by name. `--mutate` exits 1 ON DETECTION, so the gate inverts:
# exit 0 here means the leak went unnoticed and the HBM liveness gate
# has rotted.
echo "== hbm leak-mutant smoke (python tools/explore.py --mutate park-skips-film-release)"
if python tools/explore.py --mutate park-skips-film-release > "$SMOKE_DIR/hbm_mutant.log" 2>&1; then
    echo "   seeded HBM leak mutant NOT detected — PROTO-HBM gate rotted"
    cat "$SMOKE_DIR/hbm_mutant.log"
    exit 1
fi
grep -q "PROTOCHECK VIOLATION PROTO-HBM" "$SMOKE_DIR/hbm_mutant.log" || {
    echo "   mutant flagged, but not by PROTO-HBM:"
    cat "$SMOKE_DIR/hbm_mutant.log"
    exit 1
}

# metrics registry selftest + bench trajectory report (ISSUE 10
# satellites): the registry's record -> exposition -> lint -> percentile
# loop must close with zero renders, and the committed BENCH_r*.json
# captures must still parse into the one-table perf trajectory —
# non-zero here means the bench JSON schema drifted. The regenerated
# table is committed as BENCH_REPORT.md; refresh it after a capture.
echo "== metrics selftest + bench trajectory report"
python -m tpu_pbrt.obs --metrics-selftest
python tools/bench_report.py > "$SMOKE_DIR/bench_report.md"
if ! diff -q "$SMOKE_DIR/bench_report.md" BENCH_REPORT.md >/dev/null 2>&1; then
    echo "   BENCH_REPORT.md is stale — regenerate with:"
    echo "   python tools/bench_report.py > BENCH_REPORT.md"
    exit 1
fi

if [[ "${1:-}" == "--fast" ]]; then
    echo "== pytest skipped (--fast)"
    exit 0
fi

echo "== tier-1 pytest"
python -m pytest tests/ -q -m 'not slow' -p no:cacheprovider
