#!/usr/bin/env python
"""Round-4 truthful microbenchmarks for the stream tracer hot spots.

Measurement rules (see memory: tpu-measurement-pitfalls):
- the tunnel memoizes identical (executable, inputs) dispatches -> every
  repetition must differ (chained fori_loop with iteration-dependent data)
- block_until_ready does not force execution -> time a HOST FETCH of a
  scalar derived from the output
- cancel the ~100 ms tunnel RTT by differencing n=1 vs n=N chained reps

Usage: python tools/microbench4.py [which ...]
  which in {wave, sort, part, scatter, gather}; default all
"""

import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def chained(body, init, n):
    """Run body n times chained inside one jit; return final carry."""

    @partial(jax.jit, static_argnames=("reps",))
    def run(c, reps):
        return jax.lax.fori_loop(0, reps, body, c)

    def probe(out):
        leaf = jax.tree_util.tree_leaves(out)[0]
        return float(jnp.sum(jnp.ravel(leaf)[:1]))

    # warm both executables
    probe(run(init, 1))
    probe(run(init, n))

    def fetch(reps):
        t0 = time.time()
        probe(run(init, reps))
        return time.time() - t0

    t1 = min(fetch(1) for _ in range(3))
    tn = min(fetch(n) for _ in range(3))
    return (tn - t1) / (n - 1)


def bench_sort(S=1 << 17):
    """EXPAND's compaction sort: 8S elements."""
    n = 8 * S
    key0 = jnp.asarray(np.random.default_rng(0).normal(size=n), jnp.float32)
    a = jnp.arange(n, dtype=jnp.int32)

    def body4(i, c):
        k, x, y, z = c
        k = k + jnp.float32(1e-6) * i  # mutate so reps differ
        k2, x2, y2, z2 = jax.lax.sort([k, x, y, z], num_keys=1)
        return (k2, x2, y2, z2)

    t4 = chained(body4, (key0, a, a, a), 8)
    print(f"sort {n} el, 4 arrays: {t4*1e3:.2f} ms")

    def body2(i, c):
        k, x = c
        k = k + jnp.float32(1e-6) * i
        k2, x2 = jax.lax.sort([k, x], num_keys=1)
        return (k2, x2)

    t2 = chained(body2, (key0, a), 8)
    print(f"sort {n} el, 2 arrays (key+idx): {t2*1e3:.2f} ms")

    def body2g(i, c):
        k, x = c
        k = k + jnp.float32(1e-6) * i
        k2, idx = jax.lax.sort([k, a], num_keys=1)
        # 3 payload gathers like the real use
        p1 = jnp.take(x, idx)
        p2 = jnp.take(x, idx)
        p3 = jnp.take(x, idx)
        return (k2 + (p1 + p2 + p3).astype(jnp.float32) * 0, x)

    t2g = chained(body2g, (key0, a), 8)
    print(f"sort 2 arrays + 3 gathers: {t2g*1e3:.2f} ms")


def bench_sort3(S=1 << 17):
    """3-array int-key sort (tn packed into the key) vs the 4-array sort."""
    n = 8 * S
    key0 = jnp.asarray(
        np.random.default_rng(0).integers(-(2**31), 2**31 - 1, n), jnp.int32)
    a = jnp.arange(n, dtype=jnp.int32)

    def body3(i, c):
        k, x, y = c
        k = k + i
        k2, x2, y2 = jax.lax.sort([k, x, y], num_keys=1)
        return (k2, x2, y2)

    t3 = chained(body3, (key0, a, a), 8)
    print(f"sort {n} el, 3 arrays (i32 key): {t3*1e3:.2f} ms")

    def body4(i, c):
        k, x, y, z = c
        k = k + i
        k2, x2, y2, z2 = jax.lax.sort([k, x, y, z], num_keys=1)
        return (k2, x2, y2, z2)

    t4 = chained(body4, (key0, a, a, a), 8)
    print(f"sort {n} el, 4 arrays (i32 key): {t4*1e3:.2f} ms")


def bench_boxfetch(S=1 << 17, N=512):
    """Box-table fetch variants for EXPAND. Table: N nodes x 8 children x
    6 floats. Need output lane-major (6, 8, S)."""
    rng = np.random.default_rng(1)
    boxT = jnp.asarray(rng.normal(size=(6, 8, N)), jnp.float32)
    box_rows = jnp.asarray(rng.normal(size=(N, 48)), jnp.float32)
    idx0 = jnp.asarray(rng.integers(0, N, S), jnp.int32)

    def body_lane(i, c):
        acc, idx = c
        idx = (idx + i) % N
        nb = jnp.take(boxT, idx, axis=2)  # (6,8,S)
        return (acc + jnp.sum(nb[:, :, :8]), idx)

    t = chained(body_lane, (jnp.float32(0), idx0), 8)
    print(f"box fetch lane-take (6,8,N)->axis2, S={S}: {t*1e3:.2f} ms")

    def body_row(i, c):
        acc, idx = c
        idx = (idx + i) % N
        rows = jnp.take(box_rows, idx, axis=0)  # (S,48)
        nb = rows.T.reshape(6, 8, S)  # transpose to lane-major
        return (acc + jnp.sum(nb[:, :, :8]), idx)

    t = chained(body_row, (jnp.float32(0), idx0), 8)
    print(f"box fetch row-take (N,48)+transpose: {t*1e3:.2f} ms")

    def body_onehot(i, c):
        acc, idx = c
        idx = (idx + i) % N
        oh = jax.nn.one_hot(idx, N, dtype=jnp.float32)  # (S,N)
        rows = jnp.dot(oh, box_rows,
                       precision=jax.lax.Precision.DEFAULT)  # (S,48)
        nb = rows.T.reshape(6, 8, S)
        return (acc + jnp.sum(nb[:, :, :8]), idx)

    t = chained(body_onehot, (jnp.float32(0), idx0), 8)
    print(f"box fetch one-hot matmul (S,{N})@({N},48)+T: {t*1e3:.2f} ms")

    def body_onehot_T(i, c):
        acc, idx = c
        idx = (idx + i) % N
        # build one-hot transposed: (N, S) @ rows.T (48,N) x (N,S)
        oh = (idx[None, :] == jnp.arange(N)[:, None]).astype(jnp.float32)
        nb = jnp.dot(box_rows.T, oh).reshape(6, 8, S)  # (48,S)
        return (acc + jnp.sum(nb[:, :, :8]), idx)

    t = chained(body_onehot_T, (jnp.float32(0), idx0), 8)
    print(f"box fetch one-hot matmul lane-major (48,{N})@({N},S): {t*1e3:.2f} ms")


def bench_rayfetch(S=1 << 17, R=1 << 20):
    rng = np.random.default_rng(2)
    o_invT = jnp.asarray(rng.normal(size=(6, R)), jnp.float32)
    o_inv_rows = jnp.asarray(rng.normal(size=(R, 6)), jnp.float32)
    idx0 = jnp.asarray(rng.integers(0, R, S), jnp.int32)

    def body_lane(i, c):
        acc, idx = c
        idx = (idx + i) % R
        ray6 = jnp.take(o_invT, idx, axis=1)  # (6,S)
        return (acc + jnp.sum(ray6[:, :8]), idx)

    t = chained(body_lane, (jnp.float32(0), idx0), 8)
    print(f"ray fetch lane-take (6,R)->axis1, S={S}: {t*1e3:.2f} ms")

    def body_row(i, c):
        acc, idx = c
        idx = (idx + i) % R
        rows = jnp.take(o_inv_rows, idx, axis=0)  # (S,6)
        ray6 = rows.T
        return (acc + jnp.sum(ray6[:, :8]), idx)

    t = chained(body_row, (jnp.float32(0), idx0), 8)
    print(f"ray fetch row-take (R,6)+transpose: {t*1e3:.2f} ms")


def bench_rayflat(S=1 << 17, R=1 << 20):
    """6 separate flat 1D gathers (fast path?) vs the 2D takes."""
    rng = np.random.default_rng(5)
    cols = [jnp.asarray(rng.normal(size=(R,)), jnp.float32) for _ in range(6)]
    idx0 = jnp.asarray(rng.integers(0, R, S), jnp.int32)

    def body(i, c):
        acc, idx = c
        idx = (idx + i) % R
        vals = [jnp.take(col, idx) for col in cols]
        return (acc + sum(jnp.sum(v[:8]) for v in vals), idx)

    t = chained(body, (jnp.float32(0), idx0), 8)
    print(f"ray fetch 6x flat-1D take, S={S}: {t*1e3:.2f} ms")


def bench_scatter_variants(R=1 << 20, U=1 << 16):
    rng = np.random.default_rng(2)
    t0 = jnp.full((R,), 1e9, jnp.float32)
    rid_rand = jnp.asarray(rng.integers(0, R, U), jnp.int32)
    val0 = jnp.asarray(rng.normal(size=U).astype(np.float32))

    def body_min_only(i, c):
        t, rid = c
        rid = (rid + i) % R
        t2 = t.at[rid].min(val0 + i.astype(jnp.float32))
        return (t2, rid)

    t = chained(body_min_only, (t0, rid_rand), 8)
    print(f"scatter-min only, {U} random into {R}: {t*1e3:.2f} ms")

    rid_sorted = jnp.sort(rid_rand)

    def body_min_sorted(i, c):
        t, rid = c
        # keep sorted: add i then re-not... adding same i keeps sorted
        rid2 = jnp.minimum(rid + i, R - 1)
        t2 = t.at[rid2].min(val0 + i.astype(jnp.float32))
        return (t2, rid)

    t = chained(body_min_sorted, (t0, rid_sorted), 8)
    print(f"scatter-min sorted idx: {t*1e3:.2f} ms")

    def body_seg(i, c):
        t, rid = c
        rid2 = (rid + i) % R
        v = val0 + i.astype(jnp.float32)
        # sort candidates by ray (i32 fast path), segment-min via
        # reverse-cummin over runs, then scatter only run heads
        r_s, v_s = jax.lax.sort([rid2, _bits_f(v)], num_keys=1)
        v_s = _unbits_f(v_s)
        # reverse cumulative min within equal-rid runs: associative scan
        def comb(a, b):
            ra, va = a
            rb, vb = b
            keep = ra == rb
            return (ra, jnp.where(keep, jnp.minimum(va, vb), va))
        rr, vv = jax.lax.associative_scan(
            comb, (r_s[::-1], v_s[::-1]))
        rr, vv = rr[::-1], vv[::-1]
        head = jnp.concatenate(
            [jnp.ones((1,), bool), r_s[1:] != r_s[:-1]])
        sel = jnp.where(head, r_s, R)
        t2 = t.at[sel].min(vv, mode="drop")
        return (t2, rid)

    t = chained(body_seg, (t0, rid_rand), 8)
    print(f"sort+segmin+scatter-min heads: {t*1e3:.2f} ms")


def bench_rowwidth(S=1 << 17, R=1 << 20):
    """Row-gather cost vs row width and index sortedness."""
    rng = np.random.default_rng(9)
    idx_r = jnp.asarray(rng.integers(0, R, S), jnp.int32)
    idx_s = jnp.sort(idx_r)
    for W in (1, 8, 32, 128):
        tab = jnp.asarray(rng.normal(size=(R, W)), jnp.float32)

        def body(i, c, tab=tab):
            acc, idx = c
            idx = (idx + i) % R
            g = tab[idx] if W > 1 else jnp.take(tab[:, 0], idx)
            return (acc + jnp.sum(jnp.ravel(g)[:8]), idx)

        tr = chained(body, (jnp.float32(0), idx_r), 8)
        ts = chained(body, (jnp.float32(0), idx_s), 8)
        print(f"row gather W={W:3d}: random {tr*1e3:6.2f} ms | "
              f"sorted-ish {ts*1e3:6.2f} ms ({S} rows)")


def bench_sort_scale():
    for logn in (17, 20, 23):
        n = 1 << logn
        key0 = jnp.asarray(
            np.random.default_rng(0).integers(-(2**31), 2**31 - 1, n),
            jnp.int32)
        a = jnp.arange(n, dtype=jnp.int32)

        def body3(i, c):
            k, x, y = c
            k = k + i
            return tuple(jax.lax.sort([k, x, y], num_keys=1))

        t3 = chained(body3, (key0, a, a), 6)
        print(f"sort {n} el 3arr i32: {t3*1e3:.2f} ms ({t3/n*1e9:.2f} ns/el)")


def bench_i64_scatter(R=1 << 20, U=1 << 16):
    rng = np.random.default_rng(7)
    t0 = jnp.full((R,), (1 << 62), jnp.int64)
    rid0 = jnp.asarray(rng.integers(0, R, U), jnp.int32)
    val0 = jnp.asarray(rng.integers(0, 1 << 40, U), jnp.int64)

    def body(i, c):
        t, rid = c
        rid = (rid + i) % R
        t2 = t.at[rid].min(val0 + i.astype(jnp.int64))
        return (t2, rid)

    try:
        t = chained(body, (t0, rid0), 8)
        print(f"i64 scatter-min {U} into {R}: {t*1e3:.2f} ms")
    except Exception as e:  # noqa: BLE001
        print(f"i64 scatter-min failed: {type(e).__name__}: {e}")


def _bits_f(x):
    return jax.lax.bitcast_convert_type(x, jnp.int32)


def _unbits_f(x):
    return jax.lax.bitcast_convert_type(x, jnp.float32)


def bench_scatter(R=1 << 20, U=1 << 16):
    t0 = jnp.full((R,), 1e9, jnp.float32)
    rng = np.random.default_rng(2)
    rid0 = jnp.asarray(rng.integers(0, R, U), jnp.int32)
    val0 = jnp.asarray(rng.normal(size=U), jnp.float32)

    def body(i, c):
        t, rid = c
        rid = (rid + i) % R
        t2 = t.at[rid].min(val0 + i.astype(jnp.float32))
        sel = jnp.where(val0 + i.astype(jnp.float32) == t2[rid], rid, R)
        t3 = t2.at[sel].set(0.5, mode="drop")
        return (t3, rid)

    t = chained(body, (t0, rid0), 8)
    print(f"scatter-min+set {U} upd into {R}: {t*1e3:.2f} ms")


def bench_gather(C=300, L=512, CH=512):
    feat0 = jnp.asarray(
        np.random.default_rng(3).normal(size=(C, 16, 4 * L)), jnp.float32)
    tids0 = jnp.asarray(np.random.default_rng(4).integers(0, C, CH), jnp.int32)

    def body(i, c):
        acc, tids = c
        tids = (tids + i) % C
        g = feat0[tids]  # (CH, 16, 4L)
        return (acc + jnp.sum(g[:, 0, :4]), tids)

    t = chained(body, (jnp.float32(0), tids0), 8)
    mb = CH * 16 * 4 * L * 4 / 1e6
    print(f"featT gather ({CH},16,{4*L}) = {mb:.0f} MB: {t*1e3:.2f} ms "
          f"-> {mb/1e3/t:.0f} GB/s")


def bench_wave():
    from tpu_pbrt.scenes import compile_api, make_killeroo_like
    from tpu_pbrt.cameras import generate_rays
    from tpu_pbrt.accel.stream import stream_intersect, stream_traverse_stats

    api = make_killeroo_like(res=512, spp=64)
    scene, _ = compile_api(api)
    dev = scene.dev
    tp = dev["tstream"]
    R = 1 << 20
    k = jnp.arange(R, dtype=jnp.int32)
    pix = k % (512 * 512)
    pf = jnp.stack([(pix % 512).astype(jnp.float32) + 0.5,
                    (pix // 512).astype(jnp.float32) + 0.5], -1)
    o, d, _ = generate_rays(scene.camera, pf, jnp.zeros_like(pf))

    @partial(jax.jit, static_argnames=("reps",))
    def run(o, d, reps):
        def body(i, acc):
            # jitter origins so every wave differs (anti-memoization)
            oo = o + jnp.float32(1e-4) * (i + 1)
            h = stream_intersect(tp, dev["tri_verts"], oo, d, jnp.inf)
            return acc + jnp.sum(jnp.where(jnp.isfinite(h.t), h.t, 0.0))
        return jax.lax.fori_loop(0, reps, body, jnp.float32(0))

    float(run(o, d, 1))
    float(run(o, d, 3))

    def fetch(reps):
        t0 = time.time()
        float(run(o, d, reps))
        return time.time() - t0

    t1 = min(fetch(1) for _ in range(2))
    t3 = min(fetch(3) for _ in range(2))
    per = (t3 - t1) / 2
    print(f"camera wave 1M rays: {per*1e3:.0f} ms -> {R/per/1e6:.2f} Mray/s")

    n_exp, n_tl, n_drop, iters = jax.jit(
        stream_traverse_stats, static_argnames=("any_hit",)
    )(tp, o, d, jnp.inf, any_hit=False)
    print(f"  pairs={int(n_exp)} leaf-slots={int(n_tl)} drops={int(n_drop)} "
          f"iters={int(iters)}")


if __name__ == "__main__":
    which = sys.argv[1:] or ["wave", "sort", "sort3", "box", "ray",
                             "scatter", "gather"]
    print(f"backend={jax.default_backend()}")
    if "wave" in which:
        bench_wave()
    if "sort" in which:
        bench_sort()
    if "sort3" in which:
        bench_sort3()
    if "box" in which:
        bench_boxfetch()
    if "ray" in which:
        bench_rayfetch()
    if "rayflat" in which:
        bench_rayflat()
    if "rowwidth" in which:
        bench_rowwidth()
    if "sortscale" in which:
        bench_sort_scale()
    if "i64" in which:
        bench_i64_scatter()
    if "scatterv" in which:
        bench_scatter_variants()
    if "scatter" in which:
        bench_scatter()
    if "gather" in which:
        bench_gather()
