#!/usr/bin/env python
"""Live-capture perf regression gate (ISSUE 15): compare a FRESH bench
JSON line against the committed BENCH_r*.json trajectory and exit
non-zero, naming the regressed metric, when the new capture falls
outside per-metric tolerances.

    python bench.py ... | tail -1 > /tmp/fresh.json
    python tools/bench_gate.py /tmp/fresh.json

The committed baseline is the LATEST non-outage capture (bench_report's
outage rule: an explicit `infra_outage` flag, or value 0.0 with an
`error` — both mean the run measured the infrastructure, not the
renderer). A fresh capture that is itself an outage is EXEMPT (exit 0
with a loud note): the gate guards perf regressions, and failing CI
because the TPU pool was unreachable would train everyone to ignore it.

Per-metric tolerances (a metric is compared only when BOTH sides carry
it — early captures predate the telemetry block, and TPU_PBRT_METRICS=0
nulls the phase shares):

- Mray/s (`value`): fresh >= baseline * (1 - 10%)
- `mean_wave_occupancy`: fresh >= baseline - 0.05 (absolute)
- `telemetry.host_overlap_fraction`: fresh >= baseline - 0.10
- `vmem_headroom`: fresh >= baseline - 0.05
- phase wall-time shares (from `telemetry.phase_seconds`): each
  phase's share of total within +-0.15 of the baseline's share

Higher-is-better only — a fresh capture that BEATS the baseline always
passes; commit it as the next BENCH_r* and the bar moves up.

`--selftest` proves all three behaviors with no fresh capture: the
baseline gates itself (pass), a committed outage row is exempt, and a
synthetic 50% throughput regression fails naming the metric. That is
the tools/ci.sh scope-stage leg. Standard library only.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: (metric label, kind, tolerance) — kind "rel" floors at base*(1-tol),
#: "abs" floors at base-tol; both one-sided (higher is better)
TOLERANCES = (
    ("value", "rel", 0.10),
    ("mean_wave_occupancy", "abs", 0.05),
    ("vmem_headroom", "abs", 0.05),
    ("telemetry.host_overlap_fraction", "abs", 0.10),
)
#: two-sided tolerance on each phase's share of total phase seconds
PHASE_SHARE_TOL = 0.15


def is_outage(line: Dict[str, Any]) -> bool:
    """bench_report.py's rule, shared verbatim: the explicit flag, or
    the pre-PR-4 shape (zero throughput + an error string)."""
    return bool(line.get("infra_outage")) or (
        line.get("value") == 0.0 and bool(line.get("error"))
    )


def load_capture(path: str) -> Dict[str, Any]:
    """A bench line: either bench.py's raw JSON line, or a committed
    BENCH_r* wrapper ({"n", "cmd", "rc", "parsed"}) whose `parsed` is
    the line."""
    with open(path) as f:
        doc = json.load(f)
    if "parsed" in doc and isinstance(doc.get("parsed"), dict):
        return doc["parsed"]
    return doc


def committed_baseline(
    pattern: Optional[str] = None,
) -> Tuple[Optional[str], Optional[Dict[str, Any]]]:
    """(run name, bench line) of the latest committed non-outage
    capture, or (None, None) when the trajectory has no usable row."""
    paths = sorted(glob.glob(pattern or os.path.join(REPO, "BENCH_r*.json")))
    for path in reversed(paths):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and parsed and not is_outage(parsed):
            name = doc.get("n") or os.path.basename(path)
            return str(name), parsed
    return None, None


def _get(line: Dict[str, Any], dotted: str) -> Optional[float]:
    cur: Any = line
    for part in dotted.split("."):
        if not isinstance(cur, dict) or cur.get(part) is None:
            return None
        cur = cur[part]
    try:
        return float(cur)
    except (TypeError, ValueError):
        return None


def _phase_shares(line: Dict[str, Any]) -> Optional[Dict[str, float]]:
    phases = (line.get("telemetry") or {}).get("phase_seconds")
    if not isinstance(phases, dict) or not phases:
        return None
    secs = {
        ph: float(agg.get("seconds", 0.0))
        for ph, agg in phases.items()
        if isinstance(agg, dict)
    }
    total = sum(secs.values())
    if total <= 0:
        return None
    return {ph: s / total for ph, s in secs.items()}


def compare(
    baseline: Dict[str, Any], fresh: Dict[str, Any]
) -> Tuple[List[str], List[str]]:
    """(failures, compared-metric notes). Failures name the metric."""
    fails: List[str] = []
    notes: List[str] = []
    for metric, kind, tol in TOLERANCES:
        base, new = _get(baseline, metric), _get(fresh, metric)
        if base is None or new is None:
            continue
        floor = base * (1.0 - tol) if kind == "rel" else base - tol
        notes.append(
            f"{metric}: {new:g} vs baseline {base:g} (floor {floor:g})"
        )
        if new < floor:
            fails.append(
                f"{metric} regressed: {new:g} < floor {floor:g} "
                f"(baseline {base:g}, tolerance "
                f"{'-' + format(tol, '.0%') if kind == 'rel' else f'-{tol}'})"
            )
    b_sh, f_sh = _phase_shares(baseline), _phase_shares(fresh)
    if b_sh and f_sh:
        for ph in sorted(set(b_sh) & set(f_sh)):
            delta = f_sh[ph] - b_sh[ph]
            notes.append(
                f"phase_share[{ph}]: {f_sh[ph]:.3f} vs {b_sh[ph]:.3f}"
            )
            if abs(delta) > PHASE_SHARE_TOL:
                fails.append(
                    f"phase_share[{ph}] moved {delta:+.3f} "
                    f"(> +-{PHASE_SHARE_TOL}): the time-attribution "
                    "mix shifted, not just the throughput"
                )
    if not notes:
        fails.append(
            "no comparable metric between baseline and fresh capture "
            "(schema drift?)"
        )
    return fails, notes


def gate(fresh: Dict[str, Any], pattern: Optional[str] = None) -> int:
    if is_outage(fresh):
        print(
            "bench_gate: fresh capture is an INFRA OUTAGE "
            f"(error: {str(fresh.get('error'))[:120]!r}) — exempt, "
            "not a perf verdict"
        )
        return 0
    name, baseline = committed_baseline(pattern)
    if baseline is None:
        print("bench_gate: no committed non-outage baseline; nothing to gate")
        return 0
    fails, notes = compare(baseline, fresh)
    for n in notes:
        print(f"  {n}")
    if fails:
        for f in fails:
            print(f"FAIL bench_gate vs {name}: {f}", file=sys.stderr)
        return 1
    print(f"bench_gate OK vs {name} ({len(notes)} metric(s) compared)")
    return 0


def selftest() -> int:
    """Three behaviors, zero TPUs: self-pass, outage exemption, and a
    synthetic regression that must fail naming its metric."""
    fails: List[str] = []
    name, baseline = committed_baseline()
    if baseline is None:
        print("FAIL selftest: no committed baseline row", file=sys.stderr)
        return 1

    if gate(dict(baseline)) != 0:
        fails.append(f"baseline {name} does not pass its own gate")

    outage = {"value": 0.0, "error": "synthetic: backend unreachable"}
    if gate(outage) != 0:
        fails.append("outage capture was not exempted")

    slow = dict(baseline)
    slow["value"] = float(baseline.get("value", 0.0)) * 0.5
    c_fails, _ = compare(baseline, slow)
    if not any("value" in f for f in c_fails):
        fails.append("50% throughput regression not caught by name")

    for f in fails:
        print(f"FAIL bench_gate-selftest: {f}", file=sys.stderr)
    if not fails:
        print(f"bench_gate selftest OK (baseline: {name})")
    return 1 if fails else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python tools/bench_gate.py")
    ap.add_argument(
        "fresh", nargs="?",
        help="fresh bench JSON (bench.py line, or a BENCH_r* wrapper)",
    )
    ap.add_argument(
        "--baseline-glob", default="",
        help="override the committed-capture glob (default: repo "
             "BENCH_r*.json)",
    )
    ap.add_argument(
        "--selftest", action="store_true",
        help="self-pass + outage exemption + synthetic regression",
    )
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    if not args.fresh:
        ap.error("pass a fresh bench JSON file (or --selftest)")
    try:
        fresh = load_capture(args.fresh)
    except (OSError, ValueError) as e:
        print(f"FAIL bench_gate: unreadable capture: {e}", file=sys.stderr)
        return 1
    return gate(fresh, args.baseline_glob or None)


if __name__ == "__main__":
    sys.exit(main())
