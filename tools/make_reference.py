#!/usr/bin/env python
"""Generate the CPU reference image for the judged MSE metric.

The judged metric (BASELINE.json) is Mray/s AND per-pixel MSE vs a CPU
reference render. This script renders the killeroo-simple-class workload on
the CPU backend at high spp and caches the float32 image; bench.py loads
the cache and compares the accelerator render against it.

Run: python tools/make_reference.py   (env: MSE_RES, REF_SPP)
The cache is keyed by (res, spp) so stale files are never silently reused.
"""

import os
import sys

# Pin the CPU platform BEFORE any jax import: the axon TPU plugin overrides
# JAX_PLATFORMS, so jax.config.update is the binding control.
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REF_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "refimg")


def reference_path(res: int, spp: int) -> str:
    return os.path.join(REF_DIR, f"killeroo_cpu_{res}x{res}_{spp}spp.npz")


def make_reference(res: int, spp: int, quiet: bool = False):
    """Render the reference on CPU and cache it. Returns (image, mray/s)."""
    import numpy as np

    from tpu_pbrt.scenes import compile_api, make_killeroo_like

    assert jax.devices()[0].platform == "cpu", jax.devices()
    api = make_killeroo_like(res=res, spp=spp)
    scene, integ = compile_api(api)
    result = integ.render(scene)
    img = np.asarray(result.image, np.float32)
    os.makedirs(REF_DIR, exist_ok=True)
    np.savez_compressed(
        reference_path(res, spp),
        image=img,
        res=res,
        spp=spp,
        mray_per_sec=result.mray_per_sec,
        seconds=result.seconds,
    )
    if not quiet:
        print(
            f"reference {res}x{res}@{spp}spp: cpu {result.mray_per_sec:.3f} Mray/s, "
            f"{result.seconds:.1f}s -> {reference_path(res, spp)}"
        )
    return img, result.mray_per_sec


if __name__ == "__main__":
    res = int(os.environ.get("MSE_RES", "128"))
    spp = int(os.environ.get("REF_SPP", "256"))
    make_reference(res, spp)
