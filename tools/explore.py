#!/usr/bin/env python
"""Bounded exhaustive explorer for the serve/dispatch protocol
(analysis layer 6 — the dynamic half of protocheck).

`tpu_pbrt/analysis/protocheck.py` makes a whole RenderService run a
pure deterministic function of an explicit decision sequence (the
VirtualClock seam + stub chunk dispatches). This tool enumerates those
sequences — job arrival orders x slice retirement orders at pipeline
depths 1-3 x fault placements from the CHAOS grammar x preempt/resume
timings — to a configurable depth, running the REAL service and
checking every PROTO-* invariant after every decision:

    python tools/explore.py --ci                      # CI smoke grid
    python tools/explore.py --nodes 200 --depth 10    # deeper search
    python tools/explore.py --mutate clock-double-sample
    python tools/explore.py --list-mutations
    python tools/explore.py --ci --trace-out /tmp/explore_trace.json

The search is a breadth-first walk over decision prefixes with
DPOR-style state pruning: each prefix is replayed on a fresh model
(cheap — stub dispatches are 2x2 numpy adds), and a prefix whose
abstract state fingerprint (job statuses/cursors/attempts, RELATIVE
backoff deadlines, window contents, tenant vtimes) was already visited
is not expanded — interleavings that merely permute into the same
protocol state are explored once.

Exit status: `--mutate` exits NON-ZERO when the seeded mutant's
expected invariant fires (the regression corpus asserts detection);
`--ci` and the default exploration exit non-zero when any violation or
determinism mismatch is found on the clean tree.

Determinism gate (PROTO-DET): every scenario's canonical full-drain
sequence is executed twice on fresh models; the event logs must be
byte-identical. `--trace-out` exports the canonical run's tpu-scope
trace (virtual-time stamps, `otherData.clock = "virtual"`) so
`tools/scope.py --check` can validate explorer timelines in CI.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, List, Optional, Tuple

# runnable as a plain script from anywhere (tools/ is not a package)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_pbrt.analysis import protocheck as pc  # noqa: E402


# --------------------------------------------------------------------------
# Exploration
# --------------------------------------------------------------------------


class Explorer:
    """Bounded BFS over decision prefixes of one scenario."""

    def __init__(
        self, scenario: pc.Scenario, seed: int = 0,
        max_nodes: int = 40, max_depth: int = 7,
    ):
        self.scenario = scenario
        self.seed = int(seed)
        self.max_nodes = int(max_nodes)
        self.max_depth = int(max_depth)
        self.nodes = 0
        self.pruned = 0
        #: [(invariant, detail, decision prefix)]
        self.violations: List[Tuple[str, str, tuple]] = []

    def _replay(self, prefix: tuple) -> Tuple[list, tuple, List[str]]:
        """Fresh model, replay `prefix`. Returns (violations,
        fingerprint, enabled decisions)."""
        with pc.make_model(self.scenario, seed=self.seed) as model:
            model.run(prefix)
            return (
                list(model.violations),
                model.fingerprint(),
                model.enabled_decisions(),
            )

    def run(self) -> "Explorer":
        frontier: List[tuple] = [()]
        seen: set = set()
        while frontier and self.nodes < self.max_nodes:
            prefix = frontier.pop(0)
            self.nodes += 1
            viol, fp, enabled = self._replay(prefix)
            if viol:
                self.violations.extend(
                    (inv, detail, prefix) for inv, detail in viol
                )
                continue  # a violating state's successors add no news
            if fp in seen:
                self.pruned += 1
                continue
            seen.add(fp)
            if len(prefix) >= self.max_depth:
                continue
            frontier.extend(prefix + (d,) for d in enabled)
        return self


def canonical_drain(
    scenario: pc.Scenario, seed: int = 0, max_steps: int = 400,
) -> Tuple[tuple, List[str], List[Tuple[str, str]]]:
    """The canonical sequential schedule: submit every job in spec
    order, then step (waiting out backoff windows) until nothing is
    schedulable. Returns (decisions, event log, violations) — the
    determinism gate replays the decisions and compares the logs."""
    decisions: List[tuple] = []
    with pc.make_model(scenario, seed=seed) as model:
        for i in range(len(scenario.jobs)):
            d = ("submit", i)
            model.apply(d)
            decisions.append(d)
        for _ in range(max_steps):
            d = _drain_pick(model.enabled_decisions())
            if d is None:
                break
            model.apply(d)
            decisions.append(d)
            if model.violations:
                break
        return tuple(decisions), list(model.log), list(model.violations)


def _drain_pick(enabled: List[tuple]) -> Optional[tuple]:
    """The canonical drain's next decision: the first step — ("step",)
    single-service, ("rstep", k) in replica order for fleet scenarios —
    else wait out a backoff window. Kill/drain decisions are never
    canonical (they are explored, not drained through)."""
    d = next((x for x in enabled if x[0] in ("step", "rstep")), None)
    if d is None:
        d = next((x for x in enabled if x[0] == "advance"), None)
    return d


def replay_log(
    scenario: pc.Scenario, decisions: tuple, seed: int = 0,
) -> List[str]:
    with pc.make_model(scenario, seed=seed) as model:
        model.run(decisions)
        return list(model.log)


def export_trace(
    scenario: pc.Scenario, path: str, seed: int = 0,
) -> Optional[str]:
    """Run the canonical drain with the tpu-scope trace armed and
    export it to `path` — virtual-time stamps throughout, so
    tools/scope.py must accept a non-wall timeline."""
    from tpu_pbrt.obs.trace import TRACE

    prev_path = TRACE._path
    TRACE.configure(path)
    TRACE.reset()
    try:
        with pc.make_model(scenario, seed=seed) as model:
            for i in range(len(scenario.jobs)):
                model.apply(("submit", i))
            for _ in range(400):
                d = _drain_pick(model.enabled_decisions())
                if d is None:
                    break
                model.apply(d)
            # export INSIDE the model context: the clock is still the
            # VirtualClock, so otherData.clock stamps "virtual"
            return TRACE.export(path)
    finally:
        TRACE.configure(prev_path)
        TRACE.reset()


# --------------------------------------------------------------------------
# CI entry point (also called by run_protocheck via importlib)
# --------------------------------------------------------------------------


def run_ci(
    seed: int = 0, max_nodes: int = 40, max_depth: int = 7,
    verbose: bool = False,
) -> List[str]:
    """The bounded clean-tree smoke: explore every scenario in the CI
    grid under the node/depth budget, and gate schedule determinism on
    every canonical drain. Returns error strings (empty = clean)."""
    errors: List[str] = []
    for scenario in pc.smoke_scenarios():
        ex = Explorer(
            scenario, seed=seed, max_nodes=max_nodes, max_depth=max_depth,
        ).run()
        if verbose:
            print(
                f"  {scenario.name}: {ex.nodes} node(s), "
                f"{ex.pruned} pruned, {len(ex.violations)} violation(s)"
            )
        for inv, detail, prefix in ex.violations[:3]:
            errors.append(
                f"[{scenario.name}] {inv}: {detail} "
                f"(decisions: {list(prefix)})"
            )
        decisions, log1, viol = canonical_drain(scenario, seed=seed)
        for inv, detail in viol[:3]:
            errors.append(
                f"[{scenario.name}] canonical drain: {inv}: {detail}"
            )
        log2 = replay_log(scenario, decisions, seed=seed)
        if log1 != log2:
            diff = next(
                (i for i, (a, b) in enumerate(zip(log1, log2)) if a != b),
                min(len(log1), len(log2)),
            )
            errors.append(
                f"[{scenario.name}] PROTO-DET: replaying the same "
                f"decision sequence diverged at event {diff} "
                f"(len {len(log1)} vs {len(log2)})"
            )
    return errors


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="bounded interleaving & fault-schedule explorer for "
        "the serve/dispatch protocol (analysis layer 6)"
    )
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--depth", type=int, default=7,
        help="max decisions per explored sequence",
    )
    ap.add_argument(
        "--nodes", type=int, default=40,
        help="max replayed prefixes per scenario",
    )
    ap.add_argument(
        "--ci", action="store_true",
        help="fixed-budget clean-tree smoke over the scenario grid",
    )
    ap.add_argument(
        "--mutate", metavar="NAME",
        help="run a seeded mutation-corpus case; exits non-zero when "
        "the expected invariant fires (detection asserted)",
    )
    ap.add_argument(
        "--list-mutations", action="store_true",
        help="list the mutation-regression corpus and exit",
    )
    ap.add_argument(
        "--trace-out", metavar="PATH",
        help="export the canonical duo-d2 drain's tpu-scope trace "
        "(virtual-time stamps) to PATH",
    )
    args = ap.parse_args(argv)

    if args.list_mutations:
        for case in pc.MUTATION_CASES:
            print(f"{case.name}: expects {case.expect} — {case.historical}")
        return 0

    if args.mutate:
        case = pc.mutation_case(args.mutate)
        viol, log = pc.run_mutation_case(
            case.name, seed=args.seed, mutate=True,
        )
        for line in log:
            print(f"  {line}")
        hit = [v for v in viol if v[0] == case.expect]
        for inv, detail in viol:
            print(f"PROTOCHECK VIOLATION {inv}: {detail}")
        if hit:
            print(
                f"mutation {case.name!r} detected by {case.expect} "
                f"(seeded regression: {case.historical})"
            )
            return 1
        print(
            f"mutation {case.name!r} NOT detected — expected "
            f"{case.expect}, got {[inv for inv, _ in viol]}"
        )
        return 0

    errors = run_ci(
        seed=args.seed, max_nodes=args.nodes, max_depth=args.depth,
        verbose=True,
    )
    if args.trace_out:
        duo = next(
            s for s in pc.smoke_scenarios() if s.name == "duo-d2"
        )
        out = export_trace(duo, args.trace_out, seed=args.seed)
        print(f"trace exported: {out}")
    for e in errors:
        print(f"PROTOCHECK {e}")
    print(
        f"protocheck explorer: {'CLEAN' if not errors else 'VIOLATIONS'} "
        f"({len(errors)} finding(s))"
    )
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
