#!/usr/bin/env python
"""Treelet/leaf re-sweep under pool waves (ROADMAP carried item).

STREAM_LEAF_TRIS (512), TPU_PBRT_SLAB (2^17) and the segmented deposit
window (pool/4) were tuned on 1M-ray fixed-batch camera waves; the regen
pool's smaller, denser waves (chunk/4 slots, camera+shadow 2R trace
batches) plausibly want a different leaf/slab/deposit balance. This
harness grids the three knobs over the POOL drain shape and emits a JSON
table, one row per configuration:

    python tools/sweep_leaf.py --out sweep.json
    python tools/sweep_leaf.py --leaf 256,512 --slab 65536,131072 \
        --deposit 0,-1 --chunk 262144 --quick

Each cell runs in a SUBPROCESS: TPU_PBRT_* knobs are snapshotted at
import (config.py contract) and STREAM_LEAF_TRIS changes the compiled
scene, so a fresh interpreter per cell is the only honest measurement.
The child renders a killeroo-like scene through the regen pool
(pool = chunk/4, the production heuristic) and reports Mray/s, wave
occupancy and wave count.

Defaults policy: the committed defaults encode LIVE v5e measurements
(accel/stream.py's STREAM_LEAF_TRIS sweep note). A CPU sweep ranks
configurations by a cost model that does not transfer to the MXU, so
this tool REFUSES to recommend moving defaults unless the measurement
ran on a TPU backend — rows carry `backend` so the reader can tell. Run
it on the next live capture; if the argmax moves, update
STREAM_LEAF_TRIS / TPU_PBRT_SLAB defaults and note the capture id.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import subprocess
import sys
import time

_CHILD = r"""
import json, os, sys, time
res = int(os.environ["SWEEP_RES"]); spp = int(os.environ["SWEEP_SPP"])
chunk = int(os.environ["SWEEP_CHUNK"])
from tpu_pbrt.scenes import compile_api, make_killeroo_like
api = make_killeroo_like(res=res, spp=spp, integrator="path", maxdepth=5,
                         n_theta=24, n_phi=48)
scene, integ = compile_api(api)
import jax
# warmup populates the jit cache; the measured leg re-renders the same
# shapes so the row is compile-free
r0 = integ.render(scene)
t0 = time.time()
r1 = integ.render(scene)
jax.block_until_ready(r1.film_state)
secs = time.time() - t0
print(json.dumps({
    "mray_per_sec": r1.rays_traced / max(secs, 1e-9) / 1e6,
    "rays": int(r1.rays_traced),
    "seconds": secs,
    "mean_wave_occupancy": r1.stats.get("mean_wave_occupancy"),
    "n_waves": r1.stats.get("n_waves"),
    "pool": r1.stats.get("pool"),
    "tracer_mode": r1.stats.get("tracer_mode"),
    "backend": jax.default_backend(),
}))
"""


def run_cell(leaf, slab, deposit, args):
    env = dict(os.environ)
    env.update(
        {
            "TPU_PBRT_LEAF_TRIS": str(leaf),
            "TPU_PBRT_SLAB": str(slab),
            "TPU_PBRT_DEPOSIT_SEG": str(deposit),
            "TPU_PBRT_CHUNK": str(args.chunk),
            "SWEEP_RES": str(args.res),
            "SWEEP_SPP": str(args.spp),
            "SWEEP_CHUNK": str(args.chunk),
        }
    )
    if args.fused is not None:
        env["TPU_PBRT_FUSED"] = args.fused
    t0 = time.time()
    try:
        out = subprocess.run(
            [sys.executable, "-c", _CHILD],
            env=env, capture_output=True, text=True,
            timeout=args.timeout,
        )
        line = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else ""
        row = json.loads(line) if line.startswith("{") else {
            "error": (out.stderr or "no output")[-800:],
        }
    except subprocess.TimeoutExpired:
        row = {"error": f"timeout after {args.timeout}s"}
    row.update(
        {
            "leaf_tris": leaf,
            "slab": slab,
            "deposit_seg": deposit,
            "chunk": args.chunk,
            "wall_seconds": round(time.time() - t0, 1),
        }
    )
    return row


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tools/sweep_leaf.py")
    ap.add_argument("--leaf", default="256,512,1024",
                    help="comma list of STREAM_LEAF_TRIS values")
    ap.add_argument("--slab", default="32768,65536,131072",
                    help="comma list of TPU_PBRT_SLAB caps")
    ap.add_argument("--deposit", default="0,-1",
                    help="comma list of TPU_PBRT_DEPOSIT_SEG windows "
                         "(0 = auto pool/4, -1 = full width)")
    ap.add_argument("--chunk", type=int, default=1 << 18,
                    help="camera rays per dispatch; the pool drains "
                         "chunk/4 slots — the swept wave shape")
    ap.add_argument("--res", type=int, default=256)
    ap.add_argument("--spp", type=int, default=4)
    ap.add_argument("--fused", default=None,
                    help="TPU_PBRT_FUSED for every cell (default: "
                         "inherit / auto)")
    ap.add_argument("--timeout", type=int, default=1800)
    ap.add_argument("--quick", action="store_true",
                    help="64x64 spp2 cells (smoke of the harness itself)")
    ap.add_argument("--out", default=None, help="write the JSON table here")
    args = ap.parse_args(argv)
    if args.quick:
        args.res, args.spp = 64, 2
        args.chunk = min(args.chunk, 1 << 14)

    grid = list(
        itertools.product(
            [int(x) for x in args.leaf.split(",") if x],
            [int(x) for x in args.slab.split(",") if x],
            [int(x) for x in args.deposit.split(",") if x != ""],
        )
    )
    rows = []
    for i, (leaf, slab, dep) in enumerate(grid):
        row = run_cell(leaf, slab, dep, args)
        rows.append(row)
        v = row.get("mray_per_sec")
        print(
            f"[{i + 1}/{len(grid)}] leaf={leaf} slab={slab} dep={dep}: "
            + (f"{v:.3f} Mray/s occ={row.get('mean_wave_occupancy')}"
               if v is not None else f"ERROR {row.get('error', '')[:120]}"),
            flush=True,
        )

    ok = [r for r in rows if "mray_per_sec" in r]
    best = max(ok, key=lambda r: r["mray_per_sec"]) if ok else None
    on_tpu = bool(ok) and all(r.get("backend") != "cpu" for r in ok)
    table = {
        "sweep": {
            "scene": f"killeroo-like res={args.res} spp={args.spp}",
            "chunk": args.chunk,
            "pool": args.chunk // 4,
            "rows": rows,
            "best": best,
            "defaults_recommendation": (
                None
                if not best
                else (
                    {
                        "leaf_tris": best["leaf_tris"],
                        "slab": best["slab"],
                        "deposit_seg": best["deposit_seg"],
                    }
                    if on_tpu
                    else "CPU sweep — ranking does not transfer to the "
                         "MXU; re-run on a live TPU before moving the "
                         "committed defaults"
                )
            ),
        }
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(table, f, indent=2)
        print(f"wrote {args.out}")
    else:
        print(json.dumps(table))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
