#!/usr/bin/env python
"""Perf breakdown of the stream tracer on the bench workload.

Times, on the live backend, for a bench-scale camera wave:
- full path-integrator chunk (the bench's unit of work)
- one closest-hit stream wave (camera rays) and one incoherent bounce-like wave
- expand/flush iteration counts + pair stats (to attribute time per step)

Usage: python tools/profile_trace.py [R_log2]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, n=3, **kw):
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    ts = []
    for _ in range(n):
        t0 = time.time()
        out = fn(*args, **kw)
        jax.block_until_ready(out)
        ts.append(time.time() - t0)
    return min(ts), out


def main():
    rlog = int(sys.argv[1]) if len(sys.argv) > 1 else 20
    R = 1 << rlog
    from tpu_pbrt.scenes import compile_api, make_killeroo_like

    api = make_killeroo_like(res=512, spp=64)
    scene, integ = compile_api(api)
    dev = scene.dev
    tp = dev["tstream"]
    print(f"backend={jax.default_backend()} R={R} treelets={tp.n_treelets} "
          f"leaf_tris={tp.leaf_tris} top_nodes={tp.top.child_idx.shape[0]}")

    from tpu_pbrt.cameras import generate_rays
    from tpu_pbrt.accel.stream import (
        stream_intersect, stream_intersect_p, stream_traverse_stats)

    # camera wave
    k = jnp.arange(R, dtype=jnp.int32)
    pix = k % (512 * 512)
    pf = jnp.stack([(pix % 512).astype(jnp.float32) + 0.5,
                    (pix // 512).astype(jnp.float32) + 0.5], -1)
    o, d, _ = generate_rays(scene.camera, pf, jnp.zeros_like(pf))
    t_cam, hit = timeit(stream_intersect, tp, dev["tri_verts"], o, d, jnp.inf)
    print(f"camera wave closest-hit: {t_cam*1e3:.1f} ms "
          f"-> {R/t_cam/1e6:.2f} Mray/s  hitrate={float(jnp.mean(hit.prim>=0)):.2f}")

    n_exp, n_tl, n_drop, iters = jax.jit(
        stream_traverse_stats, static_argnames=("any_hit",)
    )(tp, o, d, jnp.inf, any_hit=False)
    print(f"  pairs expanded={int(n_exp)} leaf-slots={int(n_tl)} "
          f"drops={int(n_drop)} iters={int(iters)}")

    # incoherent wave: random origins in scene bounds, random dirs
    rng = np.random.default_rng(0)
    lo = np.asarray(jnp.min(dev["tri_verts"].reshape(-1, 3), 0))
    hi = np.asarray(jnp.max(dev["tri_verts"].reshape(-1, 3), 0))
    o2 = jnp.asarray(rng.uniform(lo, hi, (R, 3)), jnp.float32)
    d2 = rng.normal(size=(R, 3))
    d2 = jnp.asarray(d2 / np.linalg.norm(d2, axis=-1, keepdims=True), jnp.float32)
    t_inc, hit2 = timeit(stream_intersect, tp, dev["tri_verts"], o2, d2, jnp.inf)
    print(f"incoherent wave closest-hit: {t_inc*1e3:.1f} ms "
          f"-> {R/t_inc/1e6:.2f} Mray/s  hitrate={float(jnp.mean(hit2.prim>=0)):.2f}")
    n_exp, n_tl, n_drop, iters = jax.jit(
        stream_traverse_stats, static_argnames=("any_hit",)
    )(tp, o2, d2, jnp.inf, any_hit=False)
    print(f"  pairs expanded={int(n_exp)} leaf-slots={int(n_tl)} "
          f"drops={int(n_drop)} iters={int(iters)}")

    # shadow wave
    t_sh, _ = timeit(stream_intersect_p, tp, o2, d2, 1e6)
    print(f"incoherent any-hit: {t_sh*1e3:.1f} ms -> {R/t_sh/1e6:.2f} Mray/s")

    # full path chunk at the bench's chunk size (env knobs are
    # snapshotted at import by tpu_pbrt.config — resync after mutating)
    os.environ.setdefault("TPU_PBRT_CHUNK", str(R))
    from tpu_pbrt import config

    config.reload()
    t0 = time.time()
    res = integ.render(scene, max_seconds=30)
    print(f"path render 30s-box: {res.mray_per_sec:.2f} Mray/s "
          f"rays={res.rays_traced} frac={res.completed_fraction:.3f} "
          f"wall={time.time()-t0:.1f}s")


if __name__ == "__main__":
    main()
