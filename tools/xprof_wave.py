#!/usr/bin/env python
"""Per-HLO-op TPU profile of one camera wave (memory: xprof recipe).

Usage: python tools/xprof_wave.py [top_n]
"""

import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def profile_fn(fn, *args, tdir="/tmp/xprof_wave", top_n=25):
    """Run fn twice (warm, then traced), print per-HLO self-time table."""
    out = fn(*args)
    jax.tree_util.tree_map(
        lambda x: float(jnp.sum(jnp.where(jnp.isfinite(x), x, 0.0)))
        if hasattr(x, "dtype") and x.dtype == jnp.float32 else None, out)
    os.system(f"rm -rf {tdir}")
    jax.profiler.start_trace(tdir)
    out = fn(*args)
    jax.tree_util.tree_map(
        lambda x: float(jnp.sum(jnp.where(jnp.isfinite(x), x, 0.0)))
        if hasattr(x, "dtype") and x.dtype == jnp.float32 else None, out)
    jax.profiler.stop_trace()

    files = glob.glob(f"{tdir}/plugins/profile/*/*.xplane.pb")
    from xprof.convert.raw_to_tool_data import xspace_to_tool_data

    data, _ = xspace_to_tool_data(files, "hlo_stats", {})
    tbl = json.loads(data.decode())
    if isinstance(tbl, list):
        tbl = tbl[0]
    cols = [c["id"] for c in tbl["cols"]]
    rows = [dict(zip(cols, [x.get("v") for x in r["c"]])) for r in tbl["rows"]]
    tot = sum(r["total_self_time"] for r in rows)
    print(f"device total: {tot/1e3:.0f} ms")
    for r in sorted(rows, key=lambda r: -r["total_self_time"])[:top_n]:
        expr = r["hlo_op_expression"][:100].replace(chr(10), " ")
        print(f"{r['total_self_time']/1e3:7.1f}ms n={r['occurrences']:5.0f} "
              f"{r['category'][:13]:13s} bw={r['measured_memory_bw']:7.1f} "
              f"{expr}")
    return rows


def main():
    top_n = int(sys.argv[1]) if len(sys.argv) > 1 else 25
    from tpu_pbrt.scenes import compile_api, make_killeroo_like
    from tpu_pbrt.cameras import generate_rays
    from tpu_pbrt.accel.stream import stream_intersect

    api = make_killeroo_like(res=512, spp=64)
    scene, _ = compile_api(api)
    dev = scene.dev
    tp = dev["tstream"]
    print(f"treelets={tp.n_treelets} top_nodes={tp.top.child_idx.shape[0]}")
    R = 1 << 20
    k = jnp.arange(R, dtype=jnp.int32)
    pix = k % (512 * 512)
    pf = jnp.stack([(pix % 512).astype(jnp.float32) + 0.5,
                    (pix // 512).astype(jnp.float32) + 0.5], -1)
    o, d, _ = generate_rays(scene.camera, pf, jnp.zeros_like(pf))

    def wave(o):
        h = stream_intersect(tp, dev["tri_verts"], o, d, jnp.inf)
        return h.t

    profile_fn(lambda: wave(o + 1e-4), top_n=top_n)


if __name__ == "__main__":
    main()
