#!/usr/bin/env python
"""tpu-scope: rebuild one render job's causal timeline from its trace +
flight artifacts, and CHECK that the rebuild is complete (ISSUE 15).

A depth-N pipelined serve run interleaves every job's dispatch enqueues,
retire syncs, queue waits, previews and checkpoints on one host thread.
The trace (obs/trace.py) records that interleaving as async spans keyed
by deterministic ids — job root `t:<job>`, chunk-slice `t:<job>/c<n>`,
queue-wait episode `t:<job>/q<k>` — and the per-job flight file stamps
the same trace id on every heartbeat line. This tool is the consumer
that proves those ids actually reconnect into a story:

    python tools/scope.py trace.json                      # all jobs
    python tools/scope.py trace.json --job j1             # one job
    python tools/scope.py trace.json --flight flight.jsonl --check

Per job it verifies (and `--check` exits non-zero, naming the job and
the defect, when any fails):

- the root `serve/job` async span is paired and carries a terminal
  outcome (done / failed / cancelled);
- every queue-wait episode is paired and episodes never overlap (a job
  waits in at most one episode at a time, by construction);
- every chunk-slice async span is paired, its `args.trace_id` matches
  the id prefix (depth-N interleaving attributed to the right job), and
  its dispatch->retire flow arrow is bound;
- a DONE job's ok-retired slices cover chunks 0..chunks-1 gap-free —
  recovery replays (rollback/restart re-dispatch the same chunk, park
  re-bakes it) may retire a chunk more than once, but every chunk must
  be ok-retired at least once somewhere on the timeline, and never
  beyond the traced chunk count;
- with `--flight`, the job's `flight.<job>.jsonl` parses, every line's
  trace_id matches the job's, and the submit + terminal heartbeats for
  the traced outcome are present.

Everything here reads artifacts only — no jax, no device, safe in the
leanest CI leg (the tools/ci.sh scope stage runs it against a
tracing-armed serve selftest export).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

# runnable as a plain script from anywhere (tools/ is not a package)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_pbrt.obs.flight import job_flight_path  # noqa: E402
from tpu_pbrt.obs.trace import validate_trace  # noqa: E402

#: traced outcome -> the flight phase its terminal heartbeat uses
_TERMINAL_PHASE = {
    "done": "serve_done",
    "failed": "serve_failed",
    "cancelled": "serve_cancel",
}


class JobTimeline:
    """Everything the trace recorded under one job's trace id."""

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.job_id: str = ""
        self.begin: Optional[Dict[str, Any]] = None
        self.end: Optional[Dict[str, Any]] = None
        #: id -> list of {"b": ev, "e": ev|None} slice span instances
        self.slices: Dict[str, List[Dict[str, Any]]] = {}
        #: id -> list of {"b": ev, "e": ev|None} queue-wait episodes
        self.waits: Dict[str, List[Dict[str, Any]]] = {}
        #: flow id -> starts - finishes
        self.flows: Dict[str, int] = {}
        #: X spans (dispatch, retire, preview, checkpoint, backoff...)
        self.xspans: List[Dict[str, Any]] = []
        #: instant events (preempt, sched/pick)
        self.instants: List[Dict[str, Any]] = []
        self.problems: List[str] = []

    @property
    def outcome(self) -> str:
        return (self.end or {}).get("args", {}).get("outcome", "")

    @property
    def chunks(self) -> int:
        return int((self.end or {}).get("args", {}).get("chunks", 0))

    def _pairs(self, table, key, ev, is_begin):
        insts = table.setdefault(key, [])
        if is_begin:
            insts.append({"b": ev, "e": None})
        else:
            open_ = [p for p in insts if p["e"] is None]
            if not open_:
                self.problems.append(
                    f"async end for {key} without an open begin"
                )
            else:
                open_[-1]["e"] = ev


def _group(events: List[Dict[str, Any]]) -> Dict[str, JobTimeline]:
    """Bucket every traced event under the job trace id it belongs to.
    Attribution key: the async id's `t:<job>` prefix for slice/queue
    spans, `args.trace_id` for X/instant spans."""
    jobs: Dict[str, JobTimeline] = {}

    def tl(tid: str) -> JobTimeline:
        if tid not in jobs:
            jobs[tid] = JobTimeline(tid)
        return jobs[tid]

    for ev in events:
        ph, cat = ev.get("ph"), ev.get("cat", "")
        args = ev.get("args") or {}
        if ph in ("b", "e"):
            eid = str(ev.get("id", ""))
            if cat == "job":
                t = tl(eid)
                if ph == "b":
                    if t.begin is not None:
                        t.problems.append("duplicate serve/job begin")
                    t.begin = ev
                    t.job_id = args.get("job", "")
                else:
                    if t.end is not None:
                        t.problems.append("duplicate serve/job end")
                    t.end = ev
            elif cat in ("slice", "queue"):
                tid = eid.rsplit("/", 1)[0]
                t = tl(tid)
                table = t.slices if cat == "slice" else t.waits
                t._pairs(table, eid, ev, ph == "b")
                a_tid = args.get("trace_id")
                if ph == "b" and a_tid and a_tid != tid:
                    t.problems.append(
                        f"span {eid} args.trace_id {a_tid!r} does not "
                        f"match its id prefix (misattributed slice)"
                    )
        elif ph in ("s", "f"):
            fid = str(ev.get("id", ""))
            if "/c" in fid:
                t = tl(fid.rsplit("/", 1)[0])
                t.flows[fid] = t.flows.get(fid, 0) + (1 if ph == "s" else -1)
        elif ph == "X" and args.get("trace_id") in jobs:
            tl(args["trace_id"]).xspans.append(ev)
        elif ph == "i" and args.get("trace_id") in jobs:
            tl(args["trace_id"]).instants.append(ev)
    return jobs


def _check_job(t: JobTimeline) -> List[str]:
    """The reconstruction invariants for one job. Returns defects."""
    errs = list(t.problems)
    if t.begin is None:
        errs.append("no serve/job begin span")
    if t.end is None:
        errs.append("no serve/job end span (job never reached a terminal)")
        return errs
    if t.outcome not in ("done", "failed", "cancelled", "shed"):
        errs.append(f"unknown terminal outcome {t.outcome!r}")

    # queue-wait episodes: paired + non-overlapping
    episodes = []
    for eid, insts in sorted(t.waits.items()):
        for p in insts:
            if p["e"] is None:
                errs.append(f"queue-wait {eid} never closed")
            else:
                episodes.append((p["b"]["ts"], p["e"]["ts"], eid))
    episodes.sort()
    for (_, a_end, a_id), (b_start, _, b_id) in zip(episodes, episodes[1:]):
        if b_start < a_end:
            errs.append(
                f"queue-wait episodes {a_id} and {b_id} overlap "
                "(a job waits in one episode at a time)"
            )

    # slices: paired, flow-bound, and (done) ok-retired gap-free
    ok_chunks: Dict[int, int] = {}
    for sid, insts in sorted(t.slices.items()):
        try:
            chunk = int(sid.rsplit("/c", 1)[1])
        except (IndexError, ValueError):
            errs.append(f"slice id {sid} has no /c<chunk> suffix")
            continue
        for p in insts:
            if p["e"] is None:
                errs.append(f"slice {sid} dispatched but never closed")
            elif (p["e"].get("args") or {}).get("ok"):
                ok_chunks[chunk] = ok_chunks.get(chunk, 0) + 1
        if t.flows.get(sid, 0) != 0:
            errs.append(
                f"slice {sid} flow arrow unbalanced "
                f"({t.flows[sid]:+d} start-finish)"
            )
    if t.outcome == "done":
        want = set(range(t.chunks))
        missing = sorted(want - set(ok_chunks))
        if missing:
            errs.append(
                f"done with chunks={t.chunks} but no ok-retired slice "
                f"span for chunk(s) {missing} (gap in the timeline)"
            )
        stray = sorted(set(ok_chunks) - want)
        if stray:
            errs.append(
                f"ok-retired slice span(s) for chunk(s) {stray} beyond "
                f"chunks={t.chunks}"
            )
    return errs


def _check_flight(t: JobTimeline, flight_base: str) -> List[str]:
    """Join the job's per-job flight file back onto its trace."""
    if t.outcome == "shed" or not t.job_id:
        return []  # sheds heartbeat on the MAIN file; nothing per-job
    path = job_flight_path(flight_base, t.job_id)
    errs: List[str] = []
    try:
        with open(path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
    except OSError as e:
        return [f"per-job flight file unreadable: {e}"]
    phases = set()
    for i, raw in enumerate(lines):
        try:
            rec = json.loads(raw)
        except ValueError as e:
            errs.append(f"{path}:{i + 1}: not JSON: {e}")
            continue
        phases.add(rec.get("phase", ""))
        lt = rec.get("trace_id")
        if lt and lt != t.trace_id:
            errs.append(
                f"{path}:{i + 1}: trace_id {lt!r} is not the job's "
                f"{t.trace_id!r} (flight/trace join broken)"
            )
    if "serve_submit" not in phases:
        errs.append(f"{path}: no serve_submit heartbeat")
    want = _TERMINAL_PHASE.get(t.outcome)
    if want and want not in phases:
        errs.append(
            f"{path}: traced outcome {t.outcome!r} but no {want!r} "
            f"heartbeat (saw: {sorted(phases)})"
        )
    return errs


def _render(t: JobTimeline) -> str:
    """Human-readable timeline: every reconstructed event, time-sorted."""
    rows = []
    if t.begin is not None:
        rows.append((t.begin["ts"], f"submit  {t.trace_id}"))
    for eid, insts in t.waits.items():
        for p in insts:
            dur = (p["e"]["ts"] - p["b"]["ts"]) / 1e3 if p["e"] else None
            rows.append((
                p["b"]["ts"],
                f"wait    {eid}"
                + (f"  {dur:.2f} ms" if dur is not None else "  (open!)"),
            ))
    for sid, insts in t.slices.items():
        for p in insts:
            if p["e"] is None:
                rows.append((p["b"]["ts"], f"slice   {sid}  (never closed!)"))
            else:
                ok = (p["e"].get("args") or {}).get("ok")
                dur = (p["e"]["ts"] - p["b"]["ts"]) / 1e3
                rows.append((
                    p["b"]["ts"],
                    f"slice   {sid}  {dur:.2f} ms  "
                    f"{'retired ok' if ok else 'aborted'}",
                ))
    for ev in t.xspans:
        rows.append((
            ev["ts"], f"span    {ev['name']}  {ev.get('dur', 0) / 1e3:.2f} ms"
        ))
    for ev in t.instants:
        rows.append((ev["ts"], f"mark    {ev['name']}"))
    if t.end is not None:
        rows.append((
            t.end["ts"],
            f"end     outcome={t.outcome} chunks={t.chunks}",
        ))
    rows.sort(key=lambda r: r[0])
    head = f"== {t.job_id or t.trace_id} ({t.trace_id}) =="
    return "\n".join(
        [head] + [f"  {ts / 1e3:10.2f} ms  {txt}" for ts, txt in rows]
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python tools/scope.py")
    ap.add_argument("trace", help="Chrome-trace JSON exported by a serve run")
    ap.add_argument(
        "--flight", default="",
        help="MAIN flight path the run used (per-job files are derived: "
             "flight.jsonl -> flight.<job>.jsonl); enables the join check",
    )
    ap.add_argument(
        "--job", default="", help="reconstruct only this job id"
    )
    ap.add_argument(
        "--check", action="store_true",
        help="verify every job's timeline is complete; exit non-zero "
             "naming the first defective job",
    )
    args = ap.parse_args(argv)

    errs = validate_trace(args.trace)
    if errs:
        for e in errs:
            print(f"FAIL trace: {e}", file=sys.stderr)
        return 1
    with open(args.trace) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    # the export stamps its time source (obs/trace.py clock_kind): a
    # protocheck explorer trace runs on a VirtualClock whose timeline
    # starts near zero — timestamps are virtual decision-sequence
    # seconds, not wall-clock epochs, and every check below is
    # epoch-agnostic by construction (only deltas and pairing matter)
    clock = (doc.get("otherData") or {}).get("clock", "wall")
    if clock != "wall":
        print(
            f"scope: {clock}-clock trace — timestamps are simulated "
            "decision-sequence time, not wall time"
        )
    jobs = _group(events)
    # groups with no serve/job root span are not requests: the
    # monolithic render loop tags its slices "t:render" with no job
    # lifecycle — its async pairing is already covered by the
    # validator above, and there is no submit->terminal story to check
    skipped = [
        tid for tid, t in jobs.items()
        if t.begin is None and t.end is None
    ]
    for tid in skipped:
        del jobs[tid]
    if skipped:
        print(f"scope: skipped non-job span group(s): {sorted(skipped)}")
    if args.job:
        jobs = {
            tid: t for tid, t in jobs.items()
            if t.job_id == args.job or tid == f"t:{args.job}"
        }
        if not jobs:
            print(f"FAIL no job {args.job!r} in the trace", file=sys.stderr)
            return 1

    defects = 0
    for tid in sorted(jobs):
        t = jobs[tid]
        probs = _check_job(t)
        if args.flight:
            probs += _check_flight(t, args.flight)
        if not args.check:
            print(_render(t))
        if probs:
            defects += 1
            for p in probs:
                print(
                    f"FAIL {t.job_id or tid}: {p}", file=sys.stderr
                )
    n_done = sum(1 for t in jobs.values() if t.outcome == "done")
    print(
        f"scope: {len(jobs)} job(s), {n_done} done, "
        f"{defects} with defects"
        + (f" [{clock} clock]" if clock != "wall" else "")
    )
    return 1 if defects else 0


if __name__ == "__main__":
    sys.exit(main())
